#!/usr/bin/env python
"""``sl_perf`` — per-round compute attribution report + perf
regression gate.

Two data sources, merged into one report:

* ``kind=perf`` records from a run's ``metrics.jsonl``
  (``runtime/perf.py PerfPlane``): per-participant, per-round
  ``compute | compile | dispatch | host | wait`` attribution, MFU,
  HBM watermark, compile counts and retraces;
* the ``BENCH_r*.json`` history (and the new run-scoped
  ``bench.json`` artifacts bench.py writes): the stable
  regression-tracking keys mirrored at the top of ``extra``.

Modes:

    python tools/sl_perf.py --metrics artifacts/runs/<run_id>  # report
    python tools/sl_perf.py --metrics <dir> --report out.json
    python tools/sl_perf.py --diff BENCH_r*.json               # gate
    python tools/sl_perf.py --diff BENCH_r04.json BENCH_r05.json \
        --threshold 0.15

``--diff`` compares the LAST bench record against the previous one on
the stable keys and exits 1 on any regression beyond the noise
threshold (default 15%) — the CI perf-gate job.  Improvements and
within-noise drift pass; keys missing or null on either side are
skipped (a section that never ran is not a regression).

Stdlib only: runs anywhere the repo does (CI perf-gate installs
nothing).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys

#: noise threshold: relative change beyond which a worsened stable key
#: fails the gate
DEFAULT_THRESHOLD = 0.15

#: stable bench keys: dotted path into the bench payload -> direction
#: ("up" = higher is better, "down" = lower is better).  These are the
#: keys successive BENCH_r*.json rounds mirror at fixed paths exactly
#: so this gate can diff them without knowing section nesting.
STABLE_KEYS = {
    "value": "up",                              # headline samples/s
    "extra.protocol_samples_per_sec": "up",
    "extra.split_ratio_vs_unsplit": "down",     # split slowdown factor
    "extra.cold_round_wall_s": "down",
    "extra.wire_mb_per_round": "down",
    "extra.wire_mb_per_round_compressed": "down",
    "extra.per_device_hbm_gb.total_est": "down",
    "extra.mfu.mfu_vs_datasheet": "up",
    "extra.mfu.measured_matmul_roofline_tflops": "up",
    # streaming aggregation plane (round-9): server aggregate wall per
    # client (flat-vs-fleet-width headline) and peak simultaneous
    # full-tree copies at the UPDATE barrier (O(1) memory headline)
    "extra.agg_wall_per_client_ms": "down",
    "extra.agg_peak_tree_copies": "down",
    # multi-process aggregator tree (round-12): end-to-end aggregate
    # wall per client at 10k synthetic clients through 3 real
    # aggregator processes over TCP, and the root's PartialAggregate
    # ingress bytes with the partial codec on vs raw fp32
    "extra.agg_wall_per_client_ms_10k": "down",
    "extra.agg_root_ingress_mb_ratio": "down",
    # async decoupled mode (round-10): delayed-cell throughput, the
    # delayed async/sync wall ratio (<1 = async wins under RTT), and
    # the accuracy parity delta at equal sample budget
    "extra.async_samples_per_sec": "up",
    "extra.async_wall_ratio_vs_sync": "down",
    "extra.async_accuracy_delta": "up",
    # sharded weight update + sync overlap (round-11): the serial
    # round-boundary update wall per boundary, and the fraction of it
    # hidden behind client compute
    "extra.update_bubble_ms": "down",
    "extra.update_overlap_ratio": "up",
    # closed-loop scheduler (round-13): steady-state round wall with
    # the scheduler on vs the static plan on the heterogeneous
    # simulated fleet (<1 = the control loop pays for itself), and the
    # scheduler's own decision-pass wall at 10k simulated clients (the
    # control plane must never become the bottleneck)
    "extra.sched_wall_ratio_vs_static": "down",
    "extra.sched_decision_ms_10k": "down",
    # hierarchical fleet telemetry (round-14): server-side digest
    # ingest + decision-input build per interval at 100k synthetic
    # clients, and one /metrics render under the series cap at 100k —
    # both must stay flat as the fleet grows (the digest path is
    # O(nodes + top-K), the render O(max-client-series))
    "extra.fleet_digest_ingest_ms_100k": "down",
    "extra.fleet_metrics_render_ms_100k": "down",
    # sharded event-loop broker plane (round-15): aggregate ingest
    # throughput multiplier of 4 shard processes over the 1-shard
    # baseline (>1 = the plane scales past one GIL), and the 4-vs-1
    # shard round-wall ratio on the 100k synthetic fleet round
    "extra.broker_shard_scaling": "up",
    "extra.broker_round_wall_ratio_100k": "down",
    # cross-host MPMD stage pipeline (round-16): end-to-end samples/s
    # of the 3-stage-host cell over the single-process twin (>1 =
    # spreading the hops across processes buys real throughput), and
    # the 3-host cell's absolute rate
    "extra.mpmd_scaling_3host": "up",
    "extra.mpmd_samples_per_sec": "up",
    # Pallas hot-path kernel plane (round-17): fused-kernel wall over
    # the XLA-chain wall for the codec quantize and the round-boundary
    # stage update (< 1 = the single-pass kernel wins).  Recorded only
    # on real TPU runs — the CPU interpreter cell leaves them null,
    # and the diff gate skips null keys
    "extra.quant_kernel_wall_ratio": "down",
    "extra.update_kernel_wall_ratio": "down",
}

#: absolute pins, enforced on the NEWEST record regardless of trend: a
#: "down" key must stay <= its cap, an "up" key >= it.  The split
#: ratio drifted 1.5 -> 2.1 across BENCH_r02-r05 while the
#: trend-only gate read the torn driver tails as unparseable (the
#: escaped-quote scavenge gap fixed below) — a pin cannot recalcify.
STABLE_KEY_CAPS = {
    "extra.split_ratio_vs_unsplit": 1.7,
    "extra.update_overlap_ratio": 0.5,
    # multi-process tree acceptance pins (round-12): codec'd root
    # ingress must stay <= 0.35x of raw fp32, and the 10k-client
    # aggregate wall per client must stay flat (the 100-client point
    # of the same leg measured ~1.4 ms and 10k ~0.94 ms on the r07
    # host; the absolute pin is ~1.5x the measurement so a
    # superlinear-aggregation regression cannot calcify)
    "extra.agg_root_ingress_mb_ratio": 0.35,
    "extra.agg_wall_per_client_ms_10k": 1.5,
    # closed-loop scheduler acceptance pins (round-13): the scheduler
    # must keep beating the static plan by >= 30% on the heterogeneous
    # fleet cell, and one decision pass at 10k clients must stay
    # bounded (measured ~490 ms = 0.05 ms/client, flat from 24 ->
    # 10k; the pin is host headroom, not a target — against a ~30 s
    # 10k-client round wall the pass is ~1.6%)
    "extra.sched_wall_ratio_vs_static": 0.7,
    "extra.sched_decision_ms_10k": 1000.0,
    # hierarchical fleet telemetry acceptance pins (round-14): ONE
    # interval's server-side digest ingest + decision-input build at
    # 100k synthetic clients (measured ~4 ms on the r09 host: 24 node
    # digests + advance + summary snapshot — O(nodes + watchlist),
    # not O(clients)), and one capped /metrics render at 100k
    # (~1.5 ms; the page is O(max-client-series)).  Caps are host
    # headroom over the measurement so a superlinear regression —
    # anything that re-introduces a per-client walk — cannot calcify.
    "extra.fleet_digest_ingest_ms_100k": 50.0,
    "extra.fleet_metrics_render_ms_100k": 20.0,
    # sharded broker plane acceptance pins (round-15): 4 shard
    # processes must keep ingesting >= 2x the single broker's
    # aggregate rate, and the 100k-fleet round wall through 4 shards
    # must stay <= 0.7x the 1-shard wall — a regression toward
    # re-serializing the plane (a shared lock, a single-connection
    # funnel) cannot calcify
    "extra.broker_shard_scaling": 2.0,
    "extra.broker_round_wall_ratio_100k": 0.7,
    # MPMD stage-pipeline acceptance pin (round-16): the 3-stage-host
    # cell must keep >= 1.5x the single-process twin's samples/s — a
    # regression toward re-serializing the hops (a shared lock, a
    # single-process fallback) cannot calcify
    "extra.mpmd_scaling_3host": 1.5,
}

#: attribution components of a kind=perf record, in report order
COMPONENTS = ("compute_s", "compile_s", "dispatch_s", "host_s",
              "wait_s")


# --------------------------------------------------------------------------
# bench history loading
# --------------------------------------------------------------------------

#: raw-text rescue patterns for stable keys whose JSON wrapper is
#: unrecoverable (the historical BENCH_r*.json shape: a driver wrapper
#: with ``parsed: null`` and a FRONT-TRUNCATED stdout tail — exactly
#: the gap the run-scoped bench.json artifact closes).  Only keys with
#: globally unique spellings are scavenged; ambiguous ones (e.g. the
#: many nested "samples_per_sec") are left to structured parses.
#:
#: The quotes match BOTH ``"key":`` and ``\"key\":`` — a driver tail
#: embeds the payload as a JSON string, so every quote arrives
#: backslash-escaped.  The round-11 split-ratio hunt found the old
#: plain-quote patterns silently scavenged NOTHING from r02-r05,
#: which is how a 1.5 -> 2.1 regression of a gated key calcified
#: unseen.
_NUM = r"(-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)"
_Q = r'\\?"'


def _kv_re(key: str, suffix: str = "") -> "re.Pattern":
    return re.compile(_Q + key + _Q + r":\s*" + _NUM + suffix)


_SCAVENGE_RES = {
    "value": re.compile(_Q + "value" + _Q + r":\s*" + _NUM
                        + r",\s*" + _Q + "unit" + _Q + r":\s*"
                        + _Q + "samples/sec/chip"),
    "extra.per_device_hbm_gb.total_est":
        re.compile(_Q + "per_device_hbm_gb" + _Q + r":\s*\{[^{}]*"
                   + _Q + "total_est" + _Q + r":\s*" + _NUM),
    # the split ratio has two spellings: the mirrored stable key and
    # the in-section "ratio_vs_unsplit" older records carry
    "extra.split_ratio_vs_unsplit":
        re.compile(_Q + r"(?:split_)?ratio_vs_unsplit" + _Q
                   + r":\s*" + _NUM),
}
for _k in ("protocol_samples_per_sec", "cold_round_wall_s",
           "wire_mb_per_round", "wire_mb_per_round_compressed",
           "mfu_vs_datasheet", "measured_matmul_roofline_tflops",
           "agg_wall_per_client_ms", "agg_peak_tree_copies",
           "agg_wall_per_client_ms_10k", "agg_root_ingress_mb_ratio",
           "async_samples_per_sec", "async_wall_ratio_vs_sync",
           "async_accuracy_delta", "update_bubble_ms",
           "update_overlap_ratio", "sched_wall_ratio_vs_static",
           "sched_decision_ms_10k", "fleet_digest_ingest_ms_100k",
           "fleet_metrics_render_ms_100k", "broker_shard_scaling",
           "broker_round_wall_ratio_100k", "mpmd_scaling_3host",
           "mpmd_samples_per_sec", "quant_kernel_wall_ratio",
           "update_kernel_wall_ratio"):
    _path = ("extra.mfu." + _k
             if _k.startswith(("mfu_vs", "measured_matmul"))
             else "extra." + _k)
    _SCAVENGE_RES[_path] = _kv_re(_k)


def _dig(d: dict, dotted: str):
    cur = d
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur if isinstance(cur, (int, float)) \
        and not isinstance(cur, bool) else None


def stable_values(payload: dict) -> dict:
    """Flat {stable key: value} map from a structured bench payload."""
    return {k: v for k in STABLE_KEYS
            if (v := _dig(payload, k)) is not None}


def scavenge_stable_values(text: str) -> dict:
    """Stable keys regex-rescued from raw (possibly torn) bench text."""
    out = {}
    for key, pat in _SCAVENGE_RES.items():
        m = pat.search(text)
        if m:
            out[key] = float(m.group(1))
    return out


def _extract_payload(rec: dict) -> dict | None:
    """The structured bench payload, when one survives: a plain
    payload (the new bench.json artifact), a driver wrapper with
    ``parsed`` set, or a full ``{"metric": ...}`` line in the captured
    stdout tail."""
    if not isinstance(rec, dict):
        return None
    if "metric" in rec and "extra" in rec:
        return rec
    parsed = rec.get("parsed")
    if isinstance(parsed, dict) and "extra" in parsed:
        return parsed
    tail = rec.get("tail")
    if isinstance(tail, str):
        # last parseable {"metric": ...} start wins (partial flushes
        # may precede the final emit)
        idx = tail.rfind('{"metric"')
        if idx >= 0:
            chunk = tail[idx:].strip()
            for end in (len(chunk), chunk.rfind("}") + 1):
                try:
                    cand = json.loads(chunk[:end])
                except json.JSONDecodeError:
                    continue
                if isinstance(cand, dict) and "extra" in cand:
                    return cand
    return None


def load_bench(path: str | pathlib.Path) -> dict | None:
    """Flat stable-key map for one bench record on disk; None when
    nothing at all is recoverable (e.g. the rc=124 empty round)."""
    try:
        raw = pathlib.Path(path).read_text()
        rec = json.loads(raw)
    except (OSError, json.JSONDecodeError):
        return None
    payload = _extract_payload(rec)
    text = rec.get("tail") if isinstance(rec, dict) \
        and isinstance(rec.get("tail"), str) else raw
    scavenged = scavenge_stable_values(text)
    if payload is not None:
        vals = stable_values(payload)
        # scavenge fills keys the structured payload predates (e.g.
        # r02's split ratio lived only inside its section before the
        # mirrored stable key existed)
        for k, v in scavenged.items():
            vals.setdefault(k, v)
        return vals or None
    return scavenged or None


def diff_bench(prev: dict, cur: dict,
               threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Stable-key comparison of two flat maps: per-key old/new/
    relative change and a regression verdict.  ``regressions`` lists
    the keys that worsened beyond the threshold, plus any key whose
    NEWEST value crosses its absolute pin (``STABLE_KEY_CAPS``) — a
    pinned key fails even when the round-over-round trend is flat,
    so a regression that slipped through once can never calcify."""
    keys = {}
    regressions = []
    for key, direction in STABLE_KEYS.items():
        old, new = prev.get(key), cur.get(key)
        if old is None or new is None or old == 0:
            continue
        change = (new - old) / abs(old)
        worse = change < -threshold if direction == "up" \
            else change > threshold
        keys[key] = {"old": old, "new": new,
                     "change": round(change, 4),
                     "direction": direction,
                     "regression": worse}
        if worse:
            regressions.append(key)
    for key, cap in STABLE_KEY_CAPS.items():
        new = cur.get(key)
        if new is None:
            continue
        direction = STABLE_KEYS.get(key, "down")
        pinned = new < cap if direction == "up" else new > cap
        ent = keys.setdefault(key, {"old": prev.get(key), "new": new,
                                    "change": None,
                                    "direction": direction,
                                    "regression": False})
        ent["cap"] = cap
        if pinned:
            ent["regression"] = True
            if key not in regressions:
                regressions.append(key)
    return {"threshold": threshold, "keys": keys,
            "regressions": regressions}


# --------------------------------------------------------------------------
# kind=perf attribution report
# --------------------------------------------------------------------------

def metrics_files(path: str | pathlib.Path) -> list[pathlib.Path]:
    """metrics.jsonl plus its size-rotated siblings
    (``observability.metrics-max-mb`` → ``metrics.jsonl.N``), oldest
    first, so a rotated run reads exactly like an unrotated one.
    ONE implementation for all the stdlib tools: sl_top owns it."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
    from sl_top import journal_files
    return journal_files(pathlib.Path(path))


def load_perf_records(path: str | pathlib.Path) -> list[dict]:
    """All ``kind=perf`` records from a metrics.jsonl (or a run/log
    directory holding one), rotated files included."""
    out = []
    for p in metrics_files(path):
        for line in p.read_text().splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("kind") == "perf":
                out.append(rec)
    return out


def attribution_report(records: list[dict],
                       bench: list[dict] | None = None) -> dict:
    """Per-(participant, round) attribution rows + MFU trend, plus the
    bench history's stable keys when given."""
    rows = []
    mfu_trend = []
    for rec in records:
        wall = rec.get("wall_s") or 0.0
        comps = {c: rec.get(c, 0.0) or 0.0 for c in COMPONENTS}
        row = {
            "participant": rec.get("participant") or rec.get("client"),
            "round": rec.get("round", rec.get("round_idx")),
            # pipeline hop this record ran (clients stamp their stage
            # since the MPMD plane; None for older records)
            "stage": rec.get("stage"),
            "wall_s": wall,
            **{c: round(v, 4) for c, v in comps.items()},
            "attributed_frac": (round(sum(comps.values()) / wall, 4)
                                if wall else None),
            "steps": rec.get("steps"),
            "samples": rec.get("samples"),
            "retraces": rec.get("retraces"),
        }
        for opt in ("mfu", "tflops_per_sec", "hbm_peak_bytes",
                    "compute_samples_per_s", "hbm_peak_vs_plan"):
            if rec.get(opt) is not None:
                row[opt] = rec[opt]
        rows.append(row)
        if rec.get("mfu") is not None:
            mfu_trend.append({"round": row["round"],
                              "participant": row["participant"],
                              "mfu": rec["mfu"]})
    report: dict = {"rounds": rows, "mfu_trend": mfu_trend}
    # per-hop attribution (MPMD stage pipeline): every stage-stamped
    # record — stage-host processes' inner clients included, their
    # metrics.jsonl files merge into the same load — rolls up by hop,
    # so compute|wire|wait is reported per STAGE, not just per client.
    # wire = dispatch + host (frame encode/decode + dispatch around
    # the hot loop); wait = barrier/queue waits incl. the inter-hop
    # activation/gradient queues.  Records predating the stage stamp
    # simply don't contribute.
    hops: dict = {}
    for row in rows:
        st = row.get("stage")
        if st is None:
            continue
        ent = hops.setdefault(str(st), {
            "n": 0, "wall_s": 0.0, "compute_s": 0.0, "wire_s": 0.0,
            "wait_s": 0.0, "samples": 0})
        ent["n"] += 1
        ent["wall_s"] += row["wall_s"]
        ent["compute_s"] += row["compute_s"]
        ent["wire_s"] += row["dispatch_s"] + row["host_s"]
        ent["wait_s"] += row["wait_s"]
        ent["samples"] += int(row.get("samples") or 0)
    if hops:
        report["hops"] = {
            st: {k: (round(v, 4) if isinstance(v, float) else v)
                 for k, v in ent.items()}
            for st, ent in sorted(hops.items())}
    if bench:
        report["bench_history"] = [dict(b) for b in bench]
    return report


def render_report(report: dict) -> str:
    lines = []
    rows = report.get("rounds", [])
    if rows:
        head = ("PART", "ROUND", "WALL s", "COMPUTE", "COMPILE",
                "DISPATCH", "HOST", "WAIT", "MFU")
        table = [head]
        for r in rows:
            table.append((
                str(r.get("participant") or "?"),
                str(r.get("round")),
                f"{r.get('wall_s', 0):.2f}",
                f"{r.get('compute_s', 0):.2f}",
                f"{r.get('compile_s', 0):.2f}",
                f"{r.get('dispatch_s', 0):.2f}",
                f"{r.get('host_s', 0):.2f}",
                f"{r.get('wait_s', 0):.2f}",
                ("-" if r.get("mfu") is None
                 else f"{r['mfu']:.4f}"),
            ))
        widths = [max(len(row[i]) for row in table)
                  for i in range(len(head))]
        for row in table:
            lines.append("  ".join(f"{v:<{w}}"
                                   for v, w in zip(row, widths)))
    else:
        lines.append("no kind=perf records found")
    hops = report.get("hops")
    if hops:
        lines.append("")
        lines.append("per-hop attribution (stage pipeline):")
        head = ("STAGE", "RECS", "WALL s", "COMPUTE", "WIRE", "WAIT",
                "SAMPLES")
        table = [head]
        for st, ent in hops.items():
            table.append((
                st, str(ent["n"]), f"{ent['wall_s']:.2f}",
                f"{ent['compute_s']:.2f}", f"{ent['wire_s']:.2f}",
                f"{ent['wait_s']:.2f}", str(ent["samples"])))
        widths = [max(len(row[i]) for row in table)
                  for i in range(len(head))]
        for row in table:
            lines.append("  " + "  ".join(
                f"{v:<{w}}" for v, w in zip(row, widths)))
    hist = report.get("bench_history")
    if hist:
        # stable-key trend across the given history (oldest..newest):
        # the update-bubble / split-ratio trajectory at a glance
        lines.append("")
        lines.append("stable-key trend (oldest -> newest):")
        seen_keys = sorted({k for b in hist for k in b})
        for key in seen_keys:
            vals = [(f"{b[key]:g}" if key in b else "-") for b in hist]
            pin = (f"  [pin {'>=' if STABLE_KEYS.get(key) == 'up' else '<='}"
                   f" {STABLE_KEY_CAPS[key]:g}]"
                   if key in STABLE_KEY_CAPS else "")
            lines.append(f"  {key}: " + " -> ".join(vals) + pin)
    diff = report.get("diff")
    if diff:
        lines.append("")
        lines.append(f"regression gate (threshold "
                     f"{diff['threshold']:.0%}):")
        for key, d in sorted(diff["keys"].items()):
            mark = "REGRESSION" if d["regression"] else "ok"
            change = ("" if d.get("change") is None
                      else f"{d['change']:+.1%}, ")
            cap = ""
            if d.get("cap") is not None:
                op = ">=" if d["direction"] == "up" else "<="
                cap = f", pin {op} {d['cap']:g}"
            lines.append(f"  {key}: {d['old']} -> {d['new']} "
                         f"({change}want {d['direction']}{cap}) "
                         f"[{mark}]")
        if not diff["keys"]:
            lines.append("  (no comparable stable keys)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Compute-attribution report (kind=perf records) "
                    "and bench regression gate (stable keys).")
    ap.add_argument("--metrics", default=None, metavar="DIR",
                    help="run dir or metrics.jsonl with kind=perf "
                         "records")
    ap.add_argument("--diff", nargs="+", default=None, metavar="BENCH",
                    help="bench records (oldest..newest); compares the "
                         "last against the previous and exits 1 on a "
                         "regression beyond --threshold")
    ap.add_argument("--bench", nargs="*", default=None, metavar="BENCH",
                    help="bench history to fold into the report "
                         "(no gating)")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD)
    ap.add_argument("--report", default=None, metavar="OUT.json",
                    help="also write the full report as JSON")
    args = ap.parse_args(argv)
    if not args.metrics and not args.diff:
        ap.error("need --metrics and/or --diff")

    records = load_perf_records(args.metrics) if args.metrics else []
    bench_hist = [b for p in (args.bench or [])
                  if (b := load_bench(p)) is not None]
    report = attribution_report(records, bench=bench_hist or None)

    rc = 0
    if args.diff:
        loaded = [(p, load_bench(p)) for p in args.diff]
        usable = [(p, b) for p, b in loaded if b is not None]
        for p, b in loaded:
            if b is None:
                print(f"sl_perf: skipping unparseable bench record "
                      f"{p}", file=sys.stderr)
        if len(usable) < 2:
            print("sl_perf: need at least 2 parseable bench records "
                  "to diff", file=sys.stderr)
            rc = 2
        else:
            report["diff"] = diff_bench(usable[-2][1], usable[-1][1],
                                        threshold=args.threshold)
            report["diff"]["compared"] = [usable[-2][0], usable[-1][0]]
            if report["diff"]["regressions"]:
                rc = 1

    if args.report:
        pathlib.Path(args.report).write_text(json.dumps(report,
                                                        indent=1))
    print(render_report(report))
    if rc == 1:
        print(f"\nsl_perf: PERF REGRESSION on "
              f"{report['diff']['regressions']}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
