#!/usr/bin/env python
"""``sl_perf`` — per-round compute attribution report + perf
regression gate.

Two data sources, merged into one report:

* ``kind=perf`` records from a run's ``metrics.jsonl``
  (``runtime/perf.py PerfPlane``): per-participant, per-round
  ``compute | compile | dispatch | host | wait`` attribution, MFU,
  HBM watermark, compile counts and retraces;
* the ``BENCH_r*.json`` history (and the new run-scoped
  ``bench.json`` artifacts bench.py writes): the stable
  regression-tracking keys mirrored at the top of ``extra``.

Modes:

    python tools/sl_perf.py --metrics artifacts/runs/<run_id>  # report
    python tools/sl_perf.py --metrics <dir> --report out.json
    python tools/sl_perf.py --diff BENCH_r*.json               # gate
    python tools/sl_perf.py --diff BENCH_r04.json BENCH_r05.json \
        --threshold 0.15

``--diff`` compares the LAST bench record against the previous one on
the stable keys and exits 1 on any regression beyond the noise
threshold (default 15%) — the CI perf-gate job.  Improvements and
within-noise drift pass; keys missing or null on either side are
skipped (a section that never ran is not a regression).

Stdlib only: runs anywhere the repo does (CI perf-gate installs
nothing).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys

#: noise threshold: relative change beyond which a worsened stable key
#: fails the gate
DEFAULT_THRESHOLD = 0.15

#: stable bench keys: dotted path into the bench payload -> direction
#: ("up" = higher is better, "down" = lower is better).  These are the
#: keys successive BENCH_r*.json rounds mirror at fixed paths exactly
#: so this gate can diff them without knowing section nesting.
STABLE_KEYS = {
    "value": "up",                              # headline samples/s
    "extra.protocol_samples_per_sec": "up",
    "extra.split_ratio_vs_unsplit": "down",     # split slowdown factor
    "extra.cold_round_wall_s": "down",
    "extra.wire_mb_per_round": "down",
    "extra.wire_mb_per_round_compressed": "down",
    "extra.per_device_hbm_gb.total_est": "down",
    "extra.mfu.mfu_vs_datasheet": "up",
    "extra.mfu.measured_matmul_roofline_tflops": "up",
    # streaming aggregation plane (round-9): server aggregate wall per
    # client (flat-vs-fleet-width headline) and peak simultaneous
    # full-tree copies at the UPDATE barrier (O(1) memory headline)
    "extra.agg_wall_per_client_ms": "down",
    "extra.agg_peak_tree_copies": "down",
    # async decoupled mode (round-10): delayed-cell throughput, the
    # delayed async/sync wall ratio (<1 = async wins under RTT), and
    # the accuracy parity delta at equal sample budget
    "extra.async_samples_per_sec": "up",
    "extra.async_wall_ratio_vs_sync": "down",
    "extra.async_accuracy_delta": "up",
}

#: attribution components of a kind=perf record, in report order
COMPONENTS = ("compute_s", "compile_s", "dispatch_s", "host_s",
              "wait_s")


# --------------------------------------------------------------------------
# bench history loading
# --------------------------------------------------------------------------

#: raw-text rescue patterns for stable keys whose JSON wrapper is
#: unrecoverable (the historical BENCH_r*.json shape: a driver wrapper
#: with ``parsed: null`` and a FRONT-TRUNCATED stdout tail — exactly
#: the gap the run-scoped bench.json artifact closes).  Only keys with
#: globally unique spellings are scavenged; ambiguous ones (e.g. the
#: many nested "samples_per_sec") are left to structured parses.
_NUM = r"(-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)"
_SCAVENGE_RES = {
    "value": re.compile(r'"value":\s*' + _NUM
                        + r',\s*"unit":\s*"samples/sec/chip"'),
    "extra.protocol_samples_per_sec":
        re.compile(r'"protocol_samples_per_sec":\s*' + _NUM),
    "extra.split_ratio_vs_unsplit":
        re.compile(r'"split_ratio_vs_unsplit":\s*' + _NUM),
    "extra.cold_round_wall_s":
        re.compile(r'"cold_round_wall_s":\s*' + _NUM),
    "extra.wire_mb_per_round":
        re.compile(r'"wire_mb_per_round":\s*' + _NUM),
    "extra.wire_mb_per_round_compressed":
        re.compile(r'"wire_mb_per_round_compressed":\s*' + _NUM),
    "extra.per_device_hbm_gb.total_est":
        re.compile(r'"per_device_hbm_gb":\s*\{[^{}]*"total_est":\s*'
                   + _NUM),
    "extra.mfu.mfu_vs_datasheet":
        re.compile(r'"mfu_vs_datasheet":\s*' + _NUM),
    "extra.mfu.measured_matmul_roofline_tflops":
        re.compile(r'"measured_matmul_roofline_tflops":\s*' + _NUM),
    "extra.agg_wall_per_client_ms":
        re.compile(r'"agg_wall_per_client_ms":\s*' + _NUM),
    "extra.agg_peak_tree_copies":
        re.compile(r'"agg_peak_tree_copies":\s*' + _NUM),
    "extra.async_samples_per_sec":
        re.compile(r'"async_samples_per_sec":\s*' + _NUM),
    "extra.async_wall_ratio_vs_sync":
        re.compile(r'"async_wall_ratio_vs_sync":\s*' + _NUM),
    "extra.async_accuracy_delta":
        re.compile(r'"async_accuracy_delta":\s*' + _NUM),
}


def _dig(d: dict, dotted: str):
    cur = d
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur if isinstance(cur, (int, float)) \
        and not isinstance(cur, bool) else None


def stable_values(payload: dict) -> dict:
    """Flat {stable key: value} map from a structured bench payload."""
    return {k: v for k in STABLE_KEYS
            if (v := _dig(payload, k)) is not None}


def scavenge_stable_values(text: str) -> dict:
    """Stable keys regex-rescued from raw (possibly torn) bench text."""
    out = {}
    for key, pat in _SCAVENGE_RES.items():
        m = pat.search(text)
        if m:
            out[key] = float(m.group(1))
    return out


def _extract_payload(rec: dict) -> dict | None:
    """The structured bench payload, when one survives: a plain
    payload (the new bench.json artifact), a driver wrapper with
    ``parsed`` set, or a full ``{"metric": ...}`` line in the captured
    stdout tail."""
    if not isinstance(rec, dict):
        return None
    if "metric" in rec and "extra" in rec:
        return rec
    parsed = rec.get("parsed")
    if isinstance(parsed, dict) and "extra" in parsed:
        return parsed
    tail = rec.get("tail")
    if isinstance(tail, str):
        # last parseable {"metric": ...} start wins (partial flushes
        # may precede the final emit)
        idx = tail.rfind('{"metric"')
        if idx >= 0:
            chunk = tail[idx:].strip()
            for end in (len(chunk), chunk.rfind("}") + 1):
                try:
                    cand = json.loads(chunk[:end])
                except json.JSONDecodeError:
                    continue
                if isinstance(cand, dict) and "extra" in cand:
                    return cand
    return None


def load_bench(path: str | pathlib.Path) -> dict | None:
    """Flat stable-key map for one bench record on disk; None when
    nothing at all is recoverable (e.g. the rc=124 empty round)."""
    try:
        raw = pathlib.Path(path).read_text()
        rec = json.loads(raw)
    except (OSError, json.JSONDecodeError):
        return None
    payload = _extract_payload(rec)
    if payload is not None:
        return stable_values(payload)
    text = rec.get("tail") if isinstance(rec, dict) \
        and isinstance(rec.get("tail"), str) else raw
    return scavenge_stable_values(text) or None


def diff_bench(prev: dict, cur: dict,
               threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Stable-key comparison of two flat maps: per-key old/new/
    relative change and a regression verdict.  ``regressions`` lists
    the keys that worsened beyond the threshold."""
    keys = {}
    regressions = []
    for key, direction in STABLE_KEYS.items():
        old, new = prev.get(key), cur.get(key)
        if old is None or new is None or old == 0:
            continue
        change = (new - old) / abs(old)
        worse = change < -threshold if direction == "up" \
            else change > threshold
        keys[key] = {"old": old, "new": new,
                     "change": round(change, 4),
                     "direction": direction,
                     "regression": worse}
        if worse:
            regressions.append(key)
    return {"threshold": threshold, "keys": keys,
            "regressions": regressions}


# --------------------------------------------------------------------------
# kind=perf attribution report
# --------------------------------------------------------------------------

def load_perf_records(path: str | pathlib.Path) -> list[dict]:
    """All ``kind=perf`` records from a metrics.jsonl (or a run/log
    directory holding one)."""
    p = pathlib.Path(path)
    if p.is_dir():
        p = p / "metrics.jsonl"
    if not p.exists():
        return []
    out = []
    for line in p.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("kind") == "perf":
            out.append(rec)
    return out


def attribution_report(records: list[dict],
                       bench: list[dict] | None = None) -> dict:
    """Per-(participant, round) attribution rows + MFU trend, plus the
    bench history's stable keys when given."""
    rows = []
    mfu_trend = []
    for rec in records:
        wall = rec.get("wall_s") or 0.0
        comps = {c: rec.get(c, 0.0) or 0.0 for c in COMPONENTS}
        row = {
            "participant": rec.get("participant") or rec.get("client"),
            "round": rec.get("round", rec.get("round_idx")),
            "wall_s": wall,
            **{c: round(v, 4) for c, v in comps.items()},
            "attributed_frac": (round(sum(comps.values()) / wall, 4)
                                if wall else None),
            "steps": rec.get("steps"),
            "retraces": rec.get("retraces"),
        }
        for opt in ("mfu", "tflops_per_sec", "hbm_peak_bytes",
                    "compute_samples_per_s", "hbm_peak_vs_plan"):
            if rec.get(opt) is not None:
                row[opt] = rec[opt]
        rows.append(row)
        if rec.get("mfu") is not None:
            mfu_trend.append({"round": row["round"],
                              "participant": row["participant"],
                              "mfu": rec["mfu"]})
    report: dict = {"rounds": rows, "mfu_trend": mfu_trend}
    if bench:
        report["bench_history"] = [dict(b) for b in bench]
    return report


def render_report(report: dict) -> str:
    lines = []
    rows = report.get("rounds", [])
    if rows:
        head = ("PART", "ROUND", "WALL s", "COMPUTE", "COMPILE",
                "DISPATCH", "HOST", "WAIT", "MFU")
        table = [head]
        for r in rows:
            table.append((
                str(r.get("participant") or "?"),
                str(r.get("round")),
                f"{r.get('wall_s', 0):.2f}",
                f"{r.get('compute_s', 0):.2f}",
                f"{r.get('compile_s', 0):.2f}",
                f"{r.get('dispatch_s', 0):.2f}",
                f"{r.get('host_s', 0):.2f}",
                f"{r.get('wait_s', 0):.2f}",
                ("-" if r.get("mfu") is None
                 else f"{r['mfu']:.4f}"),
            ))
        widths = [max(len(row[i]) for row in table)
                  for i in range(len(head))]
        for row in table:
            lines.append("  ".join(f"{v:<{w}}"
                                   for v, w in zip(row, widths)))
    else:
        lines.append("no kind=perf records found")
    diff = report.get("diff")
    if diff:
        lines.append("")
        lines.append(f"regression gate (threshold "
                     f"{diff['threshold']:.0%}):")
        for key, d in sorted(diff["keys"].items()):
            mark = "REGRESSION" if d["regression"] else "ok"
            lines.append(f"  {key}: {d['old']} -> {d['new']} "
                         f"({d['change']:+.1%}, want {d['direction']}) "
                         f"[{mark}]")
        if not diff["keys"]:
            lines.append("  (no comparable stable keys)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Compute-attribution report (kind=perf records) "
                    "and bench regression gate (stable keys).")
    ap.add_argument("--metrics", default=None, metavar="DIR",
                    help="run dir or metrics.jsonl with kind=perf "
                         "records")
    ap.add_argument("--diff", nargs="+", default=None, metavar="BENCH",
                    help="bench records (oldest..newest); compares the "
                         "last against the previous and exits 1 on a "
                         "regression beyond --threshold")
    ap.add_argument("--bench", nargs="*", default=None, metavar="BENCH",
                    help="bench history to fold into the report "
                         "(no gating)")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD)
    ap.add_argument("--report", default=None, metavar="OUT.json",
                    help="also write the full report as JSON")
    args = ap.parse_args(argv)
    if not args.metrics and not args.diff:
        ap.error("need --metrics and/or --diff")

    records = load_perf_records(args.metrics) if args.metrics else []
    bench_hist = [b for p in (args.bench or [])
                  if (b := load_bench(p)) is not None]
    report = attribution_report(records, bench=bench_hist or None)

    rc = 0
    if args.diff:
        loaded = [(p, load_bench(p)) for p in args.diff]
        usable = [(p, b) for p, b in loaded if b is not None]
        for p, b in loaded:
            if b is None:
                print(f"sl_perf: skipping unparseable bench record "
                      f"{p}", file=sys.stderr)
        if len(usable) < 2:
            print("sl_perf: need at least 2 parseable bench records "
                  "to diff", file=sys.stderr)
            rc = 2
        else:
            report["diff"] = diff_bench(usable[-2][1], usable[-1][1],
                                        threshold=args.threshold)
            report["diff"]["compared"] = [usable[-2][0], usable[-1][0]]
            if report["diff"]["regressions"]:
                rc = 1

    if args.report:
        pathlib.Path(args.report).write_text(json.dumps(report,
                                                        indent=1))
    print(render_report(report))
    if rc == 1:
        print(f"\nsl_perf: PERF REGRESSION on "
              f"{report['diff']['regressions']}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
