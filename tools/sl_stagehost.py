#!/usr/bin/env python
"""``sl_stagehost`` — standalone MPMD stage-host process
(``pipeline.remote``).

One later-stage host of the cross-host pipeline: connects to the
(sharded) TCP broker with the full runtime transport stack
(Reliable/Chaos/Async compose unchanged), announces itself with
STAGEHELLO, heartbeats into the server's FleetMonitor, and runs the
later-stage client slots each STAGEASSIGN hands it — activations and
input-gradients ride the broker's ``intermediate_queue_*`` /
``gradient_queue_*`` families as ordinary TENSOR/SLTC frames.  See
``runtime/stagehost.py``.

    python tools/sl_stagehost.py --config config.yaml \
        --host-id stage_host_0

The server spawns these itself when ``pipeline.hosts`` is set; start
them by hand (or under a process manager, one per host) for a real
multi-host deployment.
"""

import sys

sys.path.insert(0, ".")  # run from the repo root

from split_learning_tpu.runtime.stagehost import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
