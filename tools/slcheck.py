#!/usr/bin/env python
"""Thin wrapper: ``python tools/slcheck.py`` == ``python -m
split_learning_tpu.analysis`` from the repo root."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from split_learning_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
