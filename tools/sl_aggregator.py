#!/usr/bin/env python
"""``sl_aggregator`` — standalone aggregator-node process
(``aggregation.remote``).

One interior node of the multi-process aggregator tree: connects to
the TCP broker with the full runtime transport stack
(Reliable/Chaos/Async compose unchanged), announces itself with
AGGHELLO, heartbeats into the server's FleetMonitor, and folds the
groups each round's AGGASSIGN hands it — publishing one
PartialAggregate per group (codec'd when ``transport.codec: partial``
is set) to its parent.  See ``runtime/aggnode.py``.

    python tools/sl_aggregator.py --config config.yaml \
        --node-id aggregator_node_0

The server spawns these itself when ``aggregation.nodes`` is set;
start them by hand (or under a process manager, one per host) for a
real multi-host deployment.
"""

import sys

sys.path.insert(0, ".")  # run from the repo root

from split_learning_tpu.runtime.aggnode import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
