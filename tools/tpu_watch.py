"""Opportunistic TPU snapshot watcher (VERDICT r4 next-step #1).

The axon TPU tunnel wedges for hours at a time; three rounds of driver
benches have only ever caught it once.  This watcher gives the round
many shots instead of one: it probes the tunnel cheaply every few
minutes and, whenever the chip answers a real compile+execute, runs the
FULL ``bench.py`` and commits the resulting artifact as
``BENCH_tpu_r05.json`` so the round carries an in-repo silicon record
even if the driver's scheduled run hits a wedge.

Stages (run in order, each at most once — marker files in
``.tpu_watch/``):

* ``bench``     — full bench.py on TPU -> BENCH_tpu_r05.json
* ``flagship``  — ``tools/flagship_tpu.sh`` if present (the multi-round
                  learning run, dropped in later in the round)

Design notes:
* every probe/bench runs in a SUBPROCESS with a hard timeout — the
  wedge hangs uninterruptibly inside jax, never in this process.
* a probe is only "up" if a jitted matmul EXECUTES; ``jax.devices()``
  listing the chip proves nothing (observed: chip listed, compile hung
  6+ hours).
* commits retry on index-lock races with the interactive build session.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
STATE = REPO / ".tpu_watch"
LOG = STATE / "watch.log"
ARTIFACT = REPO / "BENCH_tpu_r05.json"
PROBE_TIMEOUT_S = 300       # first TPU compile can take ~40s; wedge hangs
BENCH_TIMEOUT_S = 4200
PROBE_INTERVAL_S = 540
try:
    DEADLINE_S = float(os.environ.get("SLT_WATCH_DEADLINE_S",
                                      11.2 * 3600))
except ValueError:   # malformed env must not kill the overnight watch
    DEADLINE_S = 11.2 * 3600

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((512, 512), jnp.bfloat16);"
    "v = jax.jit(lambda a: (a @ a).sum())(x);"
    "v.block_until_ready();"
    "print('KIND=' + jax.devices()[0].device_kind)"
)


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    with LOG.open("a") as f:
        f.write(line + "\n")


def probe() -> str | None:
    """Device kind if a jitted matmul really executed on a non-CPU chip."""
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                           capture_output=True, text=True,
                           timeout=PROBE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        log(f"probe: hung >{PROBE_TIMEOUT_S}s (tunnel wedged)")
        return None
    if r.returncode != 0:
        log(f"probe: rc={r.returncode} {r.stderr.strip()[-200:]}")
        return None
    kind = next((ln[5:] for ln in r.stdout.splitlines()
                 if ln.startswith("KIND=")), "")
    if not kind or "cpu" in kind.lower():
        log(f"probe: backend is {kind or 'unknown'!r}, not a TPU")
        return None
    return kind


def git_commit(paths: list[str], message: str) -> bool:
    for attempt in range(10):
        add = subprocess.run(["git", "-C", str(REPO), "add", *paths],
                             capture_output=True, text=True)
        if add.returncode == 0:
            c = subprocess.run(
                ["git", "-C", str(REPO), "commit", "-m", message,
                 "--only", *paths],
                capture_output=True, text=True)
            if c.returncode == 0:
                return True
            if "nothing to commit" in c.stdout + c.stderr:
                return True
            log(f"commit attempt {attempt}: {c.stderr.strip()[-200:]}")
        else:
            log(f"add attempt {attempt}: {add.stderr.strip()[-200:]}")
        time.sleep(20)  # index.lock race with the build session
    return False


def stage_bench(kind: str, history: list) -> bool:
    env = dict(os.environ)
    env["SLT_BENCH_PARTIAL_PATH"] = str(STATE / "bench_partial.json")
    env.setdefault("SLT_BENCH_BUDGET_S", "3600")
    log(f"bench: launching full bench.py on {kind}")
    try:
        r = subprocess.run([sys.executable, str(REPO / "bench.py")],
                           capture_output=True, text=True,
                           timeout=BENCH_TIMEOUT_S, cwd=str(REPO), env=env)
        out = r.stdout
    except subprocess.TimeoutExpired as e:
        log("bench: timed out; falling back to partial artifact")
        out = ""
    payload = None
    for ln in reversed(out.splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                payload = json.loads(ln)
                break
            except json.JSONDecodeError:
                continue
    if payload is None:
        partial = STATE / "bench_partial.json"
        if partial.exists():
            try:
                payload = json.loads(partial.read_text())
            except json.JSONDecodeError:
                payload = None
    if payload is None:
        log("bench: no parseable artifact")
        return False
    chip = payload.get("extra", {}).get("chip", "")
    if "cpu" in str(chip).lower() or payload.get("extra", {}).get(
            "tpu_unreachable"):
        log(f"bench: ran but landed on chip={chip!r} (wedged mid-run?); "
            "not committing as a TPU artifact")
        return False
    payload.setdefault("extra", {})["watcher"] = {
        "probe_history": history[-20:],
        "captured_at_s": round(time.time()),
        "source": "opportunistic in-round watcher (tools/tpu_watch.py)",
    }
    ARTIFACT.write_text(json.dumps(payload, indent=1) + "\n")
    # the artifact ON DISK is the prize: the stage is done once it's
    # written — a commit lost to a long index.lock race must not burn
    # another scarce unwedged-TPU window re-running the whole bench
    # (the build session / end-of-round driver commits leftovers)
    ok = git_commit([ARTIFACT.name],
                    "Record opportunistic TPU bench snapshot")
    log(f"bench: artifact chip={chip} value={payload.get('value')} "
        f"committed={ok}")
    return True


def stage_flagship(kind: str, history: list) -> bool:
    script = REPO / "tools" / "flagship_tpu.sh"
    if not script.exists():
        return False  # not ready yet; retry on a later window
    log(f"flagship: launching {script} on {kind}")
    try:
        r = subprocess.run(["bash", str(script)], cwd=str(REPO),
                           capture_output=True, text=True,
                           timeout=3 * 3600)
    except subprocess.TimeoutExpired:
        log("flagship: timed out")
        return False
    log(f"flagship: rc={r.returncode} tail={r.stdout.strip()[-200:]}")
    return r.returncode == 0


STAGES = [("bench", stage_bench), ("flagship", stage_flagship)]


def main() -> None:
    STATE.mkdir(exist_ok=True)
    pidfile = STATE / "watch.pid"
    if pidfile.exists():
        try:
            os.kill(int(pidfile.read_text()), 0)
            print("watcher already running"); return
        except (OSError, ValueError):
            pass
    pidfile.write_text(str(os.getpid()))
    log(f"watcher started, pid={os.getpid()}, deadline {DEADLINE_S/3600:.1f}h")
    t0 = time.time()
    history: list = []
    while time.time() - t0 < DEADLINE_S:
        pending = [(n, fn) for n, fn in STAGES
                   if not (STATE / f"done_{n}").exists()]
        if not pending:
            log("all stages done; exiting")
            break
        kind = probe()
        history.append({"t": round(time.time() - t0),
                        "up": bool(kind), "kind": kind})
        if kind:
            log(f"tunnel UP ({kind}); pending stages: "
                f"{[n for n, _ in pending]}")
            for name, fn in pending:
                if fn(kind, history):
                    (STATE / f"done_{name}").write_text("ok")
                else:
                    break  # chip likely wedged mid-stage; re-probe
        time.sleep(PROBE_INTERVAL_S)
    log("watcher exiting")
    pidfile.unlink(missing_ok=True)


if __name__ == "__main__":
    main()
