"""Synthesize (or re-synthesize) FLAGSHIP.json from a flagship run's
``metrics.jsonl`` — used when a run was cut short (budget, kill, round
end) and `tools/flagship.py` never reached its own summary write; the
per-round metrics sidecar is the surviving record.

    python tools/flagship_summary.py artifacts/flagship_cpu.tmp \
        --promote artifacts/flagship_cpu
"""

from __future__ import annotations

import argparse
import json
import pathlib
import shutil


def summarize(run_dir: pathlib.Path, note: str = "") -> dict:
    rows = []
    with open(run_dir / "metrics.jsonl") as f:
        for line in f:
            rec = json.loads(line)
            if "num_samples" in rec and "wall_s" in rec:
                rows.append(rec)
    traj = [{"round": r["round_idx"], "ok": r.get("ok"),
             "samples": r["num_samples"],
             "val_accuracy": r.get("val_accuracy"),
             "val_loss": r.get("val_loss"),
             "wall_s": round(r["wall_s"], 2)} for r in rows]
    accs = [t["val_accuracy"] for t in traj
            if t["val_accuracy"] is not None]
    return {
        "geometry": "baseline1: VGG16/CIFAR10 cut=7, clients [2,2], "
                    "IID (configs/baseline1.yaml)",
        "data": "synthetic CIFAR-10 stand-in (zero-egress image; "
                "class-template Gaussians, data/datasets.py) — run "
                "`python -m split_learning_tpu.data --fetch cifar10` "
                "for real bytes",
        "rounds_recorded": len(traj),
        "final_val_accuracy": accs[-1] if accs else None,
        "best_val_accuracy": max(accs) if accs else None,
        "total_wall_s": round(sum(t["wall_s"] for t in traj), 1),
        **({"note": note} if note else {}),
        "trajectory": traj,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir")
    ap.add_argument("--promote", default=None,
                    help="also copy metrics + summary to this dir "
                         "(replacing it)")
    ap.add_argument("--note", default="")
    args = ap.parse_args(argv)
    run_dir = pathlib.Path(args.run_dir)
    summary = summarize(run_dir, args.note)
    (run_dir / "FLAGSHIP.json").write_text(
        json.dumps(summary, indent=1) + "\n")
    print(json.dumps({k: v for k, v in summary.items()
                      if k != "trajectory"}, indent=1))
    if args.promote:
        dest = pathlib.Path(args.promote)
        if dest.resolve() == run_dir.resolve():
            print("already in place (promote dest == run dir)")
            return 0
        staged = [(n, (run_dir / n).read_bytes())
                  for n in ("FLAGSHIP.json", "metrics.jsonl")
                  if (run_dir / n).exists()]
        shutil.rmtree(dest, ignore_errors=True)
        dest.mkdir(parents=True)
        for name, data in staged:
            (dest / name).write_bytes(data)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
