#!/usr/bin/env python
"""Chaos sweep: fault probabilities x seeds -> pass/fail matrix.

Each cell pushes a PROTOCOL message stream (TENSOR-framed Activations
fenced by an EpochEnd) through the production transport stack —
``ReliableTransport`` over a seeded ``ChaosTransport`` over the
in-process bus — and PASSes iff the receiver sees the exact sent
sequence, in order, with nothing extra, AND the decoded stream replays
clean through the protocol-model trace validator
(``split_learning_tpu/analysis/model.py``) — so every sweep cell also
proves protocol conformance, not just byte delivery.  ``--full`` cells
additionally replay the round's ``app.log`` through the control-plane
state machines.  Because every cell is reproducible from its (fault,
probability, seed) triple, a FAIL here is a ready-made regression
test: rerun with ``--only drop:0.4 --seeds 1 --seed-base <seed>`` and
debug.

    python tools/run_chaos.py                  # default grid, 5 seeds
    python tools/run_chaos.py --seeds 20 --messages 400   # longer soak
    python tools/run_chaos.py --full           # full tiny training
                                               # round per cell (slow;
                                               # needs jax/CPU)

Exit code is non-zero when any cell fails, so it slots into CI.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import threading
import time

sys.path.insert(0, ".")  # run from the repo root

from split_learning_tpu.analysis.model import (  # noqa: E402
    validate_data_stream, validate_log,
)
from split_learning_tpu.config import ChaosConfig  # noqa: E402
from split_learning_tpu.runtime.bus import (  # noqa: E402
    InProcTransport, ReliableTransport,
)
from split_learning_tpu.runtime.chaos import ChaosTransport  # noqa: E402
from split_learning_tpu.runtime.trace import FaultCounters  # noqa: E402

QUEUE = "intermediate_queue_0_0"


def _protocol_stream(n: int) -> list[bytes]:
    """n TENSOR-framed Activations + the epoch fence, as wire bytes."""
    import numpy as np

    from split_learning_tpu.runtime import protocol as proto
    frames = [proto.encode(proto.Activation(
        data_id=f"d{i:06d}",
        data=np.full((8,), i % 7, np.float32),
        labels=np.asarray([i % 10], np.int64),
        trace=["feeder"], cluster=0)) for i in range(n)]
    frames.append(proto.encode(proto.EpochEnd(client_id="feeder")))
    return frames


def transport_cell(fault: str, prob: float, seed: int,
                   n_messages: int) -> tuple[bool, str]:
    """True iff the reliable layer fully masks this fault class."""
    kwargs = {f: 0.0 for f in ("drop", "duplicate", "reorder", "corrupt",
                               "delay")}
    if fault == "mixed":
        for f in kwargs:
            kwargs[f] = prob
    else:
        kwargs[fault] = prob
    cfg = ChaosConfig(enabled=True, seed=seed, delay_s=0.005,
                      queues=("intermediate_queue*",), **kwargs)
    bus = InProcTransport()
    fc = FaultCounters()
    # provision the redelivery budget for the injected loss regime: at
    # sustained ~2/3 per-attempt loss (mixed:0.4) the give-up odds are
    # loss^(attempts+1), so 40 attempts ≈ 5e-7/message.  The receiver's
    # gap timeout must exceed the sender's full retry horizon or a
    # skip-then-late-arrival turns into a loss.
    sender = ReliableTransport(
        ChaosTransport(bus, cfg, name="s", faults=fc), sender="s",
        patterns=("intermediate_queue*",), redeliver_s=0.05,
        max_redeliver=40, faults=fc)
    recv = ReliableTransport(bus, sender="r",
                             patterns=("intermediate_queue*",),
                             redeliver_s=0.05, max_redeliver=40,
                             gap_timeout_s=60.0, faults=fc)
    msgs = _protocol_stream(n_messages)
    t = threading.Thread(
        target=lambda: [sender.publish(QUEUE, m) for m in msgs],
        daemon=True)
    t.start()
    got = []
    for _ in msgs:
        m = recv.get(QUEUE, timeout=30.0)
        if m is None:
            break
        got.append(m)
    t.join(timeout=10)
    extra = recv.get(QUEUE, timeout=0.2)
    sender.stop(close_inner=False)
    recv.stop(close_inner=False)
    if got != msgs:
        return False, f"{len(got)}/{len(msgs)} exact"
    if extra is not None:
        return False, "phantom extra message"
    # protocol conformance: the post-transport stream must decode and
    # replay clean through the declarative data-plane model (right
    # kinds on this queue family, no duplicate data_id, no round
    # regression)
    from split_learning_tpu.runtime import protocol as proto
    try:
        decoded = [proto.decode(m) for m in got]
    except Exception as e:  # noqa: BLE001 — any decode failure fails the cell
        return False, f"undecodable frame: {type(e).__name__}"
    violations = validate_data_stream(decoded, QUEUE)
    if violations:
        return False, f"protocol: {violations[0].message}"
    snap = fc.snapshot()
    note = "+".join(f"{k[0]}{v}" for k, v in sorted(snap.items())
                    if k in ("drops", "duplicates", "reorders",
                             "corruptions", "delays"))
    return True, note or "quiet"


#: --codec: the wire compression stack the chaos cells run under
#: (int8 tiled activations + top-k EF gradients + int8-delta Updates)
#: — proving the error-feedback state and delta chain deterministic
#: UNDER faults, not just on a clean wire
CODEC_STACK = {"intermediate": "int8:64", "gradient": "topk:0.1",
               "rpc": "delta:int8"}


def full_round_cell(fault: str, prob: float, seed: int, tmp: str,
                    codec: bool = False) -> tuple[bool, str]:
    """Full 3-client round; PASS iff params match the fault-free run
    bit-for-bit (baseline computed once and cached on the function).
    ``codec=True`` runs BOTH the baseline and the chaotic cell with the
    compression stack enabled — bit-identity then proves the codecs'
    stateful parts (EF residuals, delta folds) are deterministic under
    drop/dup/reorder."""
    import numpy as np

    sys.path.insert(0, "tests")
    from test_chaos import _chaos, _round_cfg, _run_cell  # noqa: E402
    root = pathlib.Path(tmp)
    over = {"transport": {"codec": CODEC_STACK}} if codec else {}
    cache = "_base_codec" if codec else "_base"
    if not hasattr(full_round_cell, cache):
        cfg = _round_cfg(root, root / f"base{'_codec' if codec else ''}",
                         **over)
        setattr(full_round_cell, cache, _run_cell(cfg))
    base = getattr(full_round_cell, cache)
    kwargs = {f: 0.0 for f in ("drop", "duplicate", "reorder", "corrupt",
                               "delay")}
    if fault == "mixed":
        for f in kwargs:
            kwargs[f] = prob
    else:
        kwargs[fault] = prob
    cell_dir = root / f"{fault}_{prob}_{seed}"
    cfg = _round_cfg(root, cell_dir, **over)
    res = _run_cell(cfg, chaos_cfg=_chaos(seed=seed, delay_s=0.005,
                                          **kwargs), reliable=True)
    if not res.history[0].ok:
        return False, "round not ok"
    if res.history[0].num_samples != base.history[0].num_samples:
        return False, "sample count drifted"
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(base.params),
                    jax.tree_util.tree_leaves(res.params)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return False, "params not bit-identical"
    # replay the round's recorded control-plane trace through the
    # protocol state machines: a chaos run must also PROVE protocol
    # conformance, not just converge to the right bits
    log = pathlib.Path(cell_dir) / "app.log"
    if log.exists():
        violations = validate_log(log.read_text(), source=str(log))
        if violations:
            return False, f"protocol: {violations[0].message}"
    # the distributed trace must survive chaos too: merge the cell's
    # span journals, schema-validate the Perfetto export, and require
    # a fully-connected span tree (every parent id resolves) — a chaos
    # fault that orphans spans would make chaotic rounds undebuggable
    # exactly when debugging matters.  trace.json is left in the cell
    # dir (CI uploads it as a workflow artifact).
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
    import sl_trace
    files = sl_trace.find_span_files(cell_dir)
    if not files:
        return False, "no span journals (tracing disabled?)"
    spans = sl_trace.load_spans(files)
    errs = sl_trace.validate_spans(spans)
    if errs:
        return False, f"spans: {errs[0]}"
    orphans = sl_trace.orphan_spans(spans)
    if orphans:
        return False, f"{len(orphans)} orphan spans"
    trace = sl_trace.build_trace(spans)
    terr = sl_trace.validate_trace(trace)
    if terr:
        return False, f"trace: {terr[0]}"
    (pathlib.Path(cell_dir) / "trace.json").write_text(
        json.dumps(trace))
    report = sl_trace.critical_path(spans)
    if not report:
        return False, "no train span in merged trace"
    (pathlib.Path(cell_dir) / "critical_path.json").write_text(
        json.dumps(report, indent=2))
    return True, "bit-identical+conformant+traced"


def fleet_cell(tmp: str, seed: int = 7) -> tuple[bool, str]:
    """Live-telemetry chaos cell: a 3-client round (2 feeders + 1
    head) with one client's rpc traffic delay-injected, heartbeats at
    a short interval and the HTTP exporter on an ephemeral port.
    PASSes iff (a) the FleetMonitor marked the delayed client
    degraded/straggler mid-round AND the round still completed, (b)
    ``/metrics`` served parseable Prometheus text mid-round (format
    lint), and (c) ``sl_top``'s renderer produced the fleet table from
    the live ``/fleet`` snapshot.  Writes ``fleet.json`` (the final
    snapshot) into the cell dir for CI artifact upload."""
    import threading as _threading
    import urllib.request

    sys.path.insert(0, "tests")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
    import sl_top
    from test_chaos import _round_cfg  # noqa: E402

    from split_learning_tpu.runtime.client import ProtocolClient
    from split_learning_tpu.runtime.server import ProtocolServer
    from split_learning_tpu.runtime.telemetry import lint_prometheus

    interval = 0.25
    cell_dir = pathlib.Path(tmp) / "fleet"
    cfg = _round_cfg(pathlib.Path(tmp), cell_dir, observability={
        "heartbeat_interval": interval, "liveness_timeout": 8.0,
        "http_port": 0})
    slow = "client_1_1"
    # every rpc frame from the slow client (heartbeats included) held
    # ~8 intervals with p=0.6: fresh-beat gaps blow past the
    # straggler threshold, and the late arrivals land stale (the
    # dup/reorder-rejection path) before a fresh burst recovers it
    slow_chaos = ChaosConfig(enabled=True, seed=seed, delay=0.6,
                             delay_s=8 * interval,
                             queues=("rpc_queue",))
    bus = InProcTransport()
    fc = FaultCounters()
    server = ProtocolServer(cfg, transport=bus, client_timeout=300.0)
    url = server.exporter.url
    threads = []
    for stage, count in enumerate(cfg.clients, start=1):
        for i in range(count):
            cid = f"client_{stage}_{i}"
            t = (ChaosTransport(bus, slow_chaos, name=cid, faults=fc)
                 if cid == slow else bus)
            client = ProtocolClient(cfg, cid, stage, transport=t)
            th = _threading.Thread(target=client.run, daemon=True)
            th.start()
            threads.append(th)

    scrapes = {"ok": 0, "errs": [], "fleet": None}

    def poll_endpoint():
        while not done.is_set():
            try:
                with urllib.request.urlopen(f"{url}/metrics",
                                            timeout=2.0) as r:
                    errs = lint_prometheus(r.read().decode())
                if errs:
                    scrapes["errs"] = errs[:3]
                else:
                    scrapes["ok"] += 1
                scrapes["fleet"] = sl_top.fetch_fleet(url)
            except Exception:  # noqa: BLE001 — a truncated body /
                # json hiccup mid-teardown must not kill the poller
                # (only OSError would leave 'ok' forever 0)
                pass
            done.wait(0.5)

    done = _threading.Event()
    poller = _threading.Thread(target=poll_endpoint, daemon=True)
    poller.start()
    t0 = time.monotonic()
    try:
        res = server.serve()
    finally:
        done.set()
        poller.join(timeout=5)
    wall = time.monotonic() - t0
    for th in threads:
        th.join(timeout=30)
    # prefer the last LIVE /fleet scrape (proves the endpoint served
    # mid-round); the in-process snapshot is the fallback view
    fleet = scrapes["fleet"] or server.ctx.fleet.snapshot()
    (cell_dir / "fleet.json").write_text(json.dumps(fleet, indent=2))
    table = sl_top.render_fleet(fleet, color=False, source=url)
    (cell_dir / "fleet_table.txt").write_text(table + "\n")
    if not res.history or not res.history[0].ok:
        return False, "round not ok"
    if wall > 240:
        return False, f"round stalled ({wall:.0f}s)"
    flagged = {t["client"] for t in fleet.get("transitions", ())
               if t["to"] in ("degraded", "straggler")}
    if slow not in flagged:
        return False, f"{slow} never flagged (transitions: "\
                      f"{fleet.get('transitions')})"
    if any(t["client"] != slow and t["to"] == "lost"
           for t in fleet.get("transitions", ())):
        return False, "healthy client marked lost"
    if scrapes["errs"]:
        return False, f"/metrics lint: {scrapes['errs'][0]}"
    if scrapes["ok"] == 0:
        return False, "no successful mid-round /metrics scrape"
    if slow not in table:
        return False, "sl_top table missing the delayed client"
    straggled = any(t["client"] == slow and t["to"] == "straggler"
                    for t in fleet.get("transitions", ()))
    return True, ("straggler+recovered" if straggled
                  else "degraded") + f"+{scrapes['ok']}scrapes"


def async_cell(tmp: str, seed: int = 11) -> tuple[bool, str]:
    """Async-mode chaos cell (learning.mode: async): a 3-client round
    (2 aux-loss feeders + 1 head) under delay + drop + duplicate
    injection with the reliable layer on.  PASSes iff

    * the round completes without a barrier stall (bounded wall — the
      decoupled loops never park on gradient_queue, so an injected
      delay costs latency, not a deadlock);
    * the fold is DETERMINISTIC: a twin run with the same chaos seed
      produces bit-identical STAGE-1 aggregated params (each feeder's
      decoupled aux-step sequence depends only on its own data/rng —
      no wire cotangent to race on) and the exact same aggregation
      counter snapshot (dup drops included).  The head's shard is
      excluded: async deliberately trades the strict SDA arrival
      barrier for liveness, so the head steps in arrival order — the
      documented nondeterminism async buys its stall-freedom with;
    * stale rejections are counted EXACTLY: a directly-driven admission
      sweep over versions ``cur, cur-1, .., cur-max_staleness-1`` plus
      a duplicate must land exactly max_staleness admits, one reject,
      one dup drop — and the staleness weights must match
      ``staleness_decay ** lag`` to the bit.
    """
    import numpy as np

    sys.path.insert(0, "tests")
    from test_chaos import _chaos, _round_cfg, _run_cell  # noqa: E402

    over = dict(
        global_rounds=1,
        aggregation={"strategy": "fedavg", "sda_strict": False,
                     "sda_size": 1},
        learning={"mode": "async", "aux_head": "pooled-linear",
                  "max_staleness": 2, "staleness_decay": 0.5,
                  "async_quorum": 0, "batch_size": 4,
                  "control_count": 1, "optimizer": "adamw",
                  "learning_rate": 1e-3})
    chaos = _chaos(seed=seed, drop=0.10, duplicate=0.10, delay=0.15,
                   delay_s=0.02)

    def run(tag):
        fc = FaultCounters()
        cfg = _round_cfg(pathlib.Path(tmp),
                         pathlib.Path(tmp) / f"async_{tag}", **over)
        t0 = time.monotonic()
        res = _run_cell(cfg, chaos_cfg=chaos, reliable=True, faults=fc)
        return res, fc.snapshot(), time.monotonic() - t0

    res_a, snap_a, wall_a = run("a")
    res_b, snap_b, wall_b = run("b")
    if not (res_a.history and res_a.history[0].ok
            and res_b.history and res_b.history[0].ok):
        return False, "round not ok"
    if max(wall_a, wall_b) > 240:
        return False, f"barrier stall ({max(wall_a, wall_b):.0f}s)"
    import jax

    from split_learning_tpu.models import build_model, shard_params
    cfg_a = _round_cfg(pathlib.Path(tmp),
                       pathlib.Path(tmp) / "async_spec", **over)
    specs = build_model(cfg_a.model_key,
                        **(cfg_a.model_kwargs or {})).specs
    cut = cfg_a.topology.cut_layers[0]
    s1_a = shard_params(res_a.params, specs, 0, cut)
    s1_b = shard_params(res_b.params, specs, 0, cut)
    if not s1_a:
        return False, "no stage-1 keys in aggregated params"
    for a, b in zip(jax.tree_util.tree_leaves(s1_a),
                    jax.tree_util.tree_leaves(s1_b)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return False, "async stage-1 fold not deterministic"
    if res_a.history[0].num_samples != res_b.history[0].num_samples:
        return False, "sample count drifted"
    agg_keys = ("agg_stale_updates", "agg_stale_admits",
                "agg_dup_drops")
    counts_a = {k: snap_a.get(k, 0) for k in agg_keys}
    if counts_a != {k: snap_b.get(k, 0) for k in agg_keys}:
        return False, f"agg counters drifted: {counts_a} vs twin"

    # exact staleness accounting, driven directly (no timing): versions
    # cur .. cur-(max_staleness+1) plus a duplicate of the last admit
    from split_learning_tpu.runtime.bus import InProcTransport
    from split_learning_tpu.runtime.aggregate import StreamingFold
    from split_learning_tpu.runtime.protocol import Update
    from split_learning_tpu.runtime.server import ProtocolContext
    cfg = _round_cfg(pathlib.Path(tmp), pathlib.Path(tmp) / "admit",
                     **over)
    ctx = ProtocolContext(cfg, InProcTransport())
    ctx._cur_gen = 5
    ctx._fold = StreamingFold({1: ["c0"]}, faults=ctx.faults)

    def upd(cid, ver):
        return Update(client_id=cid, stage=1, cluster=0,
                      params={"layer1": {"w": np.ones(4, np.float32)}},
                      num_samples=8, round_idx=ver, version=ver)
    for ver in (5, 4, 3, 2):          # lag 0 fresh, 1+2 admit, 3 reject
        ctx._admit_update(upd(f"c{5 - ver}", ver))
    ctx._admit_update(upd("c1", 4))   # post-fold duplicate
    snap = ctx.faults.snapshot()
    got = {k: snap.get(k, 0) for k in agg_keys}
    want = {"agg_stale_updates": 1, "agg_stale_admits": 2,
            "agg_dup_drops": 1}
    if got != want:
        return False, f"admission counts {got} != {want}"
    # weight math: 8 + 8*0.5 + 8*0.25 folded over all-ones trees
    result = ctx._fold.finish()
    w = np.asarray(result.params["layer1"]["w"])
    if not np.allclose(w, 1.0):
        return False, f"staleness-weighted fold wrong: {w[:2]}"
    expect_w = 8 + 8 * 0.5 + 8 * 0.25
    st = ctx._fold._stages[1]
    if abs(st.total_w - expect_w) > 1e-9:
        return False, f"fold weight {st.total_w} != {expect_w}"
    return True, (f"deterministic+admitted "
                  f"({counts_a.get('agg_dup_drops', 0)} dup drops, "
                  f"{wall_a:.0f}s/{wall_b:.0f}s)")


def tree_remote_cell(tmp: str) -> tuple[bool, str]:
    """Multi-process aggregator-tree cell (aggregation.remote): a real
    TCP broker, THREE aggregator subprocesses spawned by the server
    (``aggregation.nodes: 3``), a 3-client deterministic round — and
    one aggregator process SIGKILLed mid-round, right after its group
    assignment lands.  PASSes iff

    * the round completes without a barrier stall (the killed node's
      groups degrade to the server's counted direct-to-root fallback
      drain — detected via the spawned process's exit, the same path
      FleetMonitor ``lost`` drives for adopted nodes);
    * the kind=agg record counts the node death and the fault record
      counts ``agg_l1_fallbacks`` ≥ 1 with every member still folded
      or explicitly abandoned;
    * the surviving nodes' ``kind=agg_node`` records and the tree
      topology land as artifacts (``agg_tree.json``).
    """
    import json
    import threading as _threading

    sys.path.insert(0, "tests")
    from test_chaos import _round_cfg  # noqa: E402

    from split_learning_tpu.runtime.bus import Broker
    from split_learning_tpu.runtime.chaos import make_runtime_transport
    from split_learning_tpu.runtime.client import ProtocolClient
    from split_learning_tpu.runtime.server import ProtocolServer

    cell_dir = pathlib.Path(tmp) / "tree_remote"
    cell_dir.mkdir(parents=True, exist_ok=True)
    broker = Broker("127.0.0.1", 0)
    killed = {}
    try:
        cfg = _round_cfg(
            pathlib.Path(tmp), cell_dir,
            transport={"kind": "tcp", "host": "127.0.0.1",
                       "port": broker.port},
            aggregation={"strategy": "sda", "sda_size": 2,
                         "sda_strict": True, "fan_in": 2, "levels": 2,
                         "remote": True, "nodes": 3},
            observability={"heartbeat_interval": 0.5,
                           "liveness_timeout": 15.0})
        server = ProtocolServer(cfg, client_timeout=300.0)
        threads = []
        for stage, count in enumerate(cfg.clients, start=1):
            for i in range(count):
                cid = f"client_{stage}_{i}"
                client = ProtocolClient(
                    cfg, cid, stage,
                    transport=make_runtime_transport(cfg, cid))
                th = _threading.Thread(target=client.run, daemon=True)
                th.start()
                threads.append(th)

        def killer():
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                for nid, glist in sorted(
                        server.ctx._l1_remote.items()):
                    if not glist:
                        continue
                    proc = (server.ctx._agg_nodes.get(nid)
                            or {}).get("proc")
                    if proc is not None and proc.poll() is None:
                        proc.kill()     # SIGKILL: no cleanup, no flush
                        killed["nid"] = nid
                        killed["groups"] = [g.idx for g in glist]
                        return
                time.sleep(0.05)

        kt = _threading.Thread(target=killer, daemon=True)
        kt.start()
        t0 = time.monotonic()
        res = server.serve()
        wall = time.monotonic() - t0
        kt.join(timeout=5)
        for th in threads:
            th.join(timeout=30)
        topo = {"agg_tree": server.ctx._agg_topology,
                "killed": killed,
                "fleet": (server.ctx.fleet.snapshot()
                          if server.ctx.fleet is not None else {})}
        (cell_dir / "agg_tree.json").write_text(
            json.dumps(topo, indent=2, default=str))
    finally:
        broker.close()
    if not res.history or not res.history[0].ok:
        return False, "round not ok"
    if wall > 240:
        return False, f"round stalled ({wall:.0f}s)"
    if not killed:
        return False, "no aggregator process killed (assignment never " \
                      "observed)"
    recs = []
    for p in cell_dir.rglob("metrics.jsonl"):
        if p.is_symlink():
            continue
        for line in p.read_text().splitlines():
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    agg = [r for r in recs if r.get("kind") == "agg"]
    if not agg:
        return False, "no kind=agg record"
    if agg[-1].get("node_deaths", 0) < 1:
        return False, "node death not counted on the agg record"
    if agg[-1].get("remote_nodes", 0) < 3:
        return False, f"expected 3 remote nodes, saw " \
                      f"{agg[-1].get('remote_nodes')}"
    faults = [r for r in recs if r.get("kind") == "faults"]
    snap = faults[-1] if faults else {}
    fallbacks = snap.get("agg_l1_fallbacks", 0)
    if not fallbacks:
        return False, "agg_l1_fallbacks never counted"
    node_recs = [r for r in recs if r.get("kind") == "agg_node"]
    if not node_recs:
        return False, "no kind=agg_node records from surviving nodes"
    abandoned = snap.get("agg_fallback_abandons", 0)
    return True, (f"killed {killed['nid']} "
                  f"(groups {killed['groups']}), "
                  f"fallbacks={fallbacks} abandoned={abandoned} "
                  f"survivor_folds={sum(r.get('folded', 0) for r in node_recs)} "
                  f"[{wall:.0f}s]")


def overlap_cell(tmp: str, seed: int = 13) -> tuple[bool, str]:
    """Sync-overlap chaos cell (learning.sync-overlap): a 3-client
    sync round with the round-boundary overlap ON, under drop +
    duplicate + delay injection with the reliable layer masking.
    PASSes iff

    * the rounds complete without a barrier stall (bounded wall — the
      overlap's speculative ticks hand any control frame back to the
      lifecycle loop in arrival order, so nothing can park);
    * the aggregated params are BIT-IDENTICAL to a fault-free,
      overlap-OFF twin: the speculation (prefetch + stale-seed
      forwards, spliced or discarded with rng/loader state restored)
      must be invisible to training semantics even while chaos
      reorders the wire around it;
    * the overlap actually ran (kind=overlap records in the cell's
      metrics).
    """
    import numpy as np

    sys.path.insert(0, "tests")
    from test_chaos import _chaos, _round_cfg, _run_cell  # noqa: E402

    chaos = _chaos(seed=seed, drop=0.10, duplicate=0.10, delay=0.15,
                   delay_s=0.02)
    fc = FaultCounters()
    cfg_c = _round_cfg(pathlib.Path(tmp),
                       pathlib.Path(tmp) / "overlap_chaos",
                       global_rounds=2,
                       learning={"sync_overlap": True})
    t0 = time.monotonic()
    res_c = _run_cell(cfg_c, chaos_cfg=chaos, reliable=True, faults=fc)
    wall = time.monotonic() - t0
    cfg_b = _round_cfg(pathlib.Path(tmp),
                       pathlib.Path(tmp) / "overlap_base",
                       global_rounds=2)
    res_b = _run_cell(cfg_b)
    if not (res_c.history and all(h.ok for h in res_c.history)
            and res_b.history and all(h.ok for h in res_b.history)):
        return False, "round not ok"
    if wall > 240:
        return False, f"barrier stall ({wall:.0f}s)"
    import jax
    la = jax.tree_util.tree_leaves(res_c.params)
    lb = jax.tree_util.tree_leaves(res_b.params)
    if len(la) != len(lb) or any(
            np.asarray(a).tobytes() != np.asarray(b).tobytes()
            for a, b in zip(la, lb)):
        return False, "overlap+chaos fold not bit-identical"
    if [h.num_samples for h in res_c.history] \
            != [h.num_samples for h in res_b.history]:
        return False, "sample counts drifted"
    import glob as _glob
    import json as _json
    n_ovl = 0
    for p in _glob.glob(str(pathlib.Path(tmp) / "overlap_chaos"
                            / "**" / "metrics.jsonl"), recursive=True):
        for line in open(p):
            try:
                rec = _json.loads(line)
            except _json.JSONDecodeError:
                continue
            if rec.get("kind") == "overlap":
                n_ovl += 1
    if not n_ovl:
        return False, "no overlap activity recorded"
    return True, (f"bit-identical through chaos "
                  f"({n_ovl} overlap ticks records, {wall:.0f}s)")


def sched_cell(tmp: str, seed: int = 17) -> tuple[bool, str]:
    """Closed-loop scheduler chaos cell (scheduler.enabled): a
    heterogeneous 6-client round (synthetic-client substrate,
    ``runtime/simfleet.py``, against the real server/telemetry/
    aggregation planes) with ONE injected compute-straggler (device
    rate 10x slow) and ONE wire-straggler (wire time ~6x compute),
    with duplicate+reorder chaos on the rpc queue.  PASSes iff

    * every round completes (the scheduler must never stall a round);
    * BOTH stragglers are attributed correctly and demoted with their
      knobs retuned: the compute-straggler gets the wider staleness
      window + quorum exemption, the wire-straggler the heavier
      intermediate codec (asserted from the decision journal's
      attribution + knob details);
    * the decisions journal validates (``validate_journal``: every
      control action fully attributable — SC001's runtime twin);
    * /fleet carries the scheduler view (cluster map + per-client
      SCHED column source) and sl_top renders it.

    Writes ``sched.json`` (decisions + final fleet snapshot) into the
    cell dir for CI artifact upload."""
    from split_learning_tpu.config import from_dict
    from split_learning_tpu.runtime.log import Logger
    from split_learning_tpu.runtime.scheduler import validate_journal
    from split_learning_tpu.runtime.server import ProtocolServer
    from split_learning_tpu.runtime.simfleet import (
        SimClientSpec, SyntheticFleet,
    )

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
    import sl_top

    cell_dir = pathlib.Path(tmp) / "sched"
    cell_dir.mkdir(parents=True, exist_ok=True)
    n1, heads = 6, 1
    cfg = from_dict({
        "model": "KWT", "dataset": "SPEECHCOMMANDS",
        "clients": [n1, heads], "global_rounds": 3,
        "synthetic_size": 48, "val_max_batches": 1,
        "val_batch_size": 16,
        "model_kwargs": {"embed_dim": 16, "num_heads": 2,
                         "mlp_dim": 32},
        "log_path": str(cell_dir),
        "learning": {"batch_size": 4},
        "topology": {"cut_layers": [2]},
        "checkpoint": {"save": False, "validate": False,
                       "directory": str(cell_dir / "ckpt")},
        "observability": {"heartbeat_interval": 0.25,
                          "liveness_timeout": 30.0, "http_port": 0},
        # evict-after high: this cell proves DEMOTION + attribution
        # (eviction has its own coverage in tests/test_scheduler.py)
        "scheduler": {"enabled": True, "warmup_rounds": 1,
                      "evict_after": 10, "barrier_grace_s": 0.5},
    })
    # one compute-straggler, one wire-straggler, four healthy + a head
    n_layers, speed, samples = 4, 100.0, 32
    update_bytes = 64 << 10
    specs = []
    for i in range(n1):
        sp, wire = speed, 0.0
        if i == 0:
            sp = speed / 10.0
        elif i == 1:
            wire = update_bytes / (6.0 * samples / speed)
        specs.append(SimClientSpec(
            cid=f"sim_1_{i:05d}", stage=1, compute_speed=sp,
            wire_bytes_per_s=wire, samples=samples,
            profile={"exe_time": [(1.0 / sp) / n_layers] * n_layers,
                     "size_data": [float(update_bytes)] * n_layers,
                     "speed": sp, "network": 0.0}))
    specs.append(SimClientSpec(cid="sim_2_00000", stage=2,
                               compute_speed=speed, samples=samples))
    compute_slow, wire_slow = "sim_1_00000", "sim_1_00001"

    bus = InProcTransport()
    fc = FaultCounters()
    # duplicate + reorder chaos on the rpc queue: the scheduler's
    # inputs (heartbeats, update-piggybacked telemetry) must survive
    # the staleness guard's rejections without misattributing anyone
    chaos = ChaosConfig(enabled=True, seed=seed, duplicate=0.2,
                        reorder=0.2, queues=("rpc_queue",))
    fleet_bus = ChaosTransport(bus, chaos, name="simfleet", faults=fc)
    server = ProtocolServer(cfg, transport=bus,
                            logger=Logger.for_run(cfg, "server",
                                                  console=False),
                            client_timeout=120.0)
    fleet = SyntheticFleet(fleet_bus, specs,
                           heartbeat_interval=0.25,
                           time_scale=1.0).start()
    t0 = time.monotonic()
    try:
        res = server.serve()
    finally:
        fleet.stop()
    wall = time.monotonic() - t0
    ctx = server.ctx
    decisions = list(ctx.scheduler.decisions)
    fsnap = ctx.scheduler.annotate_fleet(ctx.fleet.snapshot())
    topo = fsnap["scheduler"]
    (cell_dir / "sched.json").write_text(json.dumps(
        {"decisions": decisions, "fleet": fsnap, "wall_s": wall},
        indent=2, default=str))
    if not res.history or not all(r.ok for r in res.history):
        return False, "round not ok"
    if wall > 240:
        return False, f"round stalled ({wall:.0f}s)"
    errs = validate_journal(decisions)
    if errs:
        return False, f"journal invalid: {errs[0]}"
    demotes = {d["client"]: d["detail"] for d in decisions
               if d["action"] == "demote"}
    if compute_slow not in demotes:
        return False, f"{compute_slow} never demoted"
    if wire_slow not in demotes:
        return False, f"{wire_slow} never demoted"
    det_c, det_w = demotes[compute_slow], demotes[wire_slow]
    if det_c.get("attribution") != "compute" \
            or "staleness_bonus" not in det_c.get("knobs", {}):
        return False, (f"compute-straggler misattributed: {det_c}")
    if det_w.get("attribution") != "wire" \
            or "intermediate" not in det_w.get("knobs",
                                               {}).get("codec", {}):
        return False, f"wire-straggler misattributed: {det_w}"
    table = sl_top.render_fleet(fsnap, color=False, source="sched")
    (cell_dir / "sched_table.txt").write_text(table + "\n")
    # the SCHED column shows each client's LAST action (a later mid-
    # round drop may have overwritten demote@rN) — require both
    # stragglers to carry one, and the demotions to render in the
    # decisions tail
    if not all("@r" in (topo["actions"].get(c) or "")
               for c in (compute_slow, wire_slow)):
        return False, "sl_top SCHED column missing the stragglers"
    if "demote" not in table:
        return False, "sl_top decisions tail missing the demotions"
    healthy_demoted = [c for c in demotes
                       if c not in (compute_slow, wire_slow)]
    if healthy_demoted:
        return False, f"healthy clients demoted: {healthy_demoted}"
    return True, (f"both stragglers attributed+demoted, "
                  f"{len(decisions)} journaled decisions, "
                  f"{fc.snapshot().get('duplicates', 0)} dup "
                  f"{fc.snapshot().get('reorders', 0)} reorder "
                  f"injected [{wall:.0f}s]")


def fleet_scale_cell(tmp: str, seed: int = 23) -> tuple[bool, str]:
    """Hierarchical digest roll-up chaos cell
    (observability.digest-interval): a 24-client synthetic fleet whose
    heartbeats route through TWO in-proc aggregator-node digest
    workers, with duplicate+reorder chaos on the digest and rpc
    queues, and ONE node stopped mid-run.  PASSes iff

    * every round completes (the roll-up must never stall a round);
    * the digest path actually carried the fleet: the server folded
      FleetDigest frames (digest block in /fleet with exact state
      counts covering the routed clients);
    * the killed node's clients fall back to DIRECT heartbeats,
      counted exactly (``digest_fallbacks`` == clients routed to it);
    * NO client ever transitions to ``lost`` (the fallback drains the
      dead node's parked beats — a phantom `lost` flap is the failure
      mode this cell exists to catch);
    * chaos was real: duplicated digest/heartbeat frames were
      rejected by the (t, seq) staleness guards, never double-folded.

    Writes ``fleet_digest.json`` (final snapshot + fallback counts)
    into the cell dir for CI artifact upload."""
    import threading

    from split_learning_tpu.config import from_dict
    from split_learning_tpu.runtime.aggnode import AggregatorNode
    from split_learning_tpu.runtime.log import Logger
    from split_learning_tpu.runtime.server import ProtocolServer
    from split_learning_tpu.runtime.simfleet import (
        SyntheticFleet, hetero_fleet,
    )

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
    import sl_top

    cell_dir = pathlib.Path(tmp) / "fleet_scale"
    cell_dir.mkdir(parents=True, exist_ok=True)
    n1, heads = 24, 1
    cfg = from_dict({
        "model": "KWT", "dataset": "SPEECHCOMMANDS",
        "clients": [n1, heads], "global_rounds": 4,
        "synthetic_size": 48, "val_max_batches": 1,
        "val_batch_size": 16,
        "model_kwargs": {"embed_dim": 16, "num_heads": 2,
                         "mlp_dim": 32},
        "log_path": str(cell_dir),
        "learning": {"batch_size": 4},
        "topology": {"cut_layers": [2]},
        "checkpoint": {"save": False, "validate": False,
                       "directory": str(cell_dir / "ckpt")},
        "observability": {"heartbeat_interval": 0.2,
                          "liveness_timeout": 2.0,
                          "digest_interval": 0.3,
                          "watchlist_size": 8,
                          "max_client_series": 16,
                          "http_port": 0},
    })
    bus = InProcTransport()
    fc = FaultCounters()
    # dup + reorder on the roll-up path: duplicated heartbeats must be
    # rejected by the node monitors' staleness guard, duplicated
    # FleetDigest frames by the server's — never double-folded
    chaos = ChaosConfig(enabled=True, seed=seed, duplicate=0.2,
                        reorder=0.2,
                        queues=("digest_queue_*", "rpc_queue"))
    fleet_bus = ChaosTransport(bus, chaos, name="simfleet", faults=fc)
    # server FIRST: its startup queue purge would eat AggHello frames
    # published before it exists (the spawned-subprocess ordering)
    server = ProtocolServer(cfg, transport=bus,
                            logger=Logger.for_run(cfg, "server",
                                                  console=False),
                            client_timeout=120.0)
    # node publishes (FleetDigest frames included) ride the same
    # dup/reorder chaos: a duplicated digest must be rejected by the
    # server's (t, seq) guard, never double-folded
    nodes = [AggregatorNode(
        cfg, f"tel_node_{i}",
        transport=ChaosTransport(bus, chaos, name=f"tel_node_{i}",
                                 faults=fc),
        fold_transport=bus, digest_transport=bus)
        for i in range(2)]
    node_threads = [threading.Thread(target=n.run, daemon=True)
                    for n in nodes]
    for t in node_threads:
        t.start()
    specs = hetero_fleet(n1, heads, compute_speed=100.0, samples=32,
                         seed=seed)
    fleet = SyntheticFleet(fleet_bus, specs, heartbeat_interval=0.2,
                           time_scale=1.0).start()
    ctx = server.ctx
    state = {"route_before": {}, "killed": None}

    def killer():
        # let round 1 establish the routes, then stop one node that
        # actually serves clients (its digest thread dies with it)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            routed = dict(ctx._digest_route)
            if len(set(routed.values())) >= 2:
                break
            time.sleep(0.1)
        time.sleep(1.0)
        routed = dict(ctx._digest_route)
        state["route_before"] = routed
        victims = sorted(set(routed.values()))
        if victims:
            state["killed"] = victims[0]
            for n in nodes:
                if n.node_id == victims[0]:
                    n.stop()

    kt = threading.Thread(target=killer, daemon=True)
    kt.start()
    t0 = time.monotonic()
    try:
        res = server.serve()
    finally:
        fleet.stop()
        for n in nodes:
            n.stop()
    wall = time.monotonic() - t0
    snap = ctx.fleet.snapshot(series=False)
    faults = ctx.faults.snapshot()
    killed = state["killed"]
    expected_fallbacks = sum(
        1 for nid in state["route_before"].values() if nid == killed)
    out = {
        "wall_s": round(wall, 3), "killed_node": killed,
        "route_before": state["route_before"],
        "digest_fallbacks": faults.get("digest_fallbacks", 0),
        "expected_fallbacks": expected_fallbacks,
        "stale_digests": faults.get("stale_digests", 0),
        "stale_heartbeats": sum(
            n.faults.snapshot().get("stale_heartbeats", 0)
            for n in nodes),
        "fleet": snap,
    }
    (cell_dir / "fleet_digest.json").write_text(
        json.dumps(out, indent=2, default=str))
    table = sl_top.render_fleet(snap, color=False,
                                source="fleet-scale", top=10)
    (cell_dir / "fleet_digest_table.txt").write_text(table + "\n")
    if not res.history or not all(r.ok for r in res.history):
        return False, "round not ok"
    if killed is None:
        return False, "no digest routes established (roll-up inert)"
    if not state["route_before"]:
        return False, "no clients were routed through digest nodes"
    if faults.get("digest_fallbacks", 0) != expected_fallbacks:
        return False, (f"fallback count {faults.get('digest_fallbacks')}"
                       f" != {expected_fallbacks} clients routed to "
                       f"{killed}")
    phantom = [t for t in snap.get("transitions", ())
               if t.get("to") == "lost"
               and str(t.get("client", "")).startswith("sim_")]
    if phantom:
        return False, f"phantom lost transition(s): {phantom[:3]}"
    dig_nodes = (snap.get("digest") or {}).get("nodes") or {}
    if not dig_nodes:
        return False, "no FleetDigest ever folded at the server"
    if killed in dig_nodes:
        return False, f"dead node {killed} still in the digest fold"
    if out["stale_heartbeats"] <= 0 or out["stale_digests"] <= 0:
        return False, ("chaos injected nothing the guards rejected "
                       f"(beats={out['stale_heartbeats']} "
                       f"digests={out['stale_digests']})")
    return True, (f"{len(state['route_before'])} routed, "
                  f"{expected_fallbacks} fell back on {killed} death, "
                  f"{out['stale_heartbeats']} dup beats + "
                  f"{out['stale_digests']} dup digests rejected, "
                  f"0 phantom lost [{wall:.0f}s]")


def broker_shard_cell(tmp: str, seed: int = 29) -> tuple[bool, str]:
    """Sharded broker plane chaos cell (broker.shards): a 3-client
    deterministic round over TWO real broker shard processes with the
    reliable layer on and drop+dup+reorder injected on the data-plane
    queues — and the shard owning the forward data queue SIGKILLed
    mid-round (its queued frames die with it), then respawned on the
    same port.  PASSes iff

    * the round completes without a barrier stall (per-shard reconnect
      backoff + at-least-once redelivery absorb the restart; the
      surviving shard's traffic never stalls);
    * aggregation is BIT-IDENTICAL to a fault-free twin run over a
      fresh 2-shard plane (chaos off, no kill) — the exactness bar
      every chaos cell in this suite holds;
    * fault counts are exact where exactness is provable: zero
      ``lost``, zero ``gave_up`` (nothing may be silently dropped),
      with ``reconnects`` >= 1 (the kill was real) and
      ``redeliveries`` >= 1 (the at-least-once envelope repaired real
      loss), all recorded in the artifact;
    * the killed shard actually carried data-plane traffic before the
      kill (a kill on an idle shard proves nothing).

    Writes ``broker_shard.json`` (kill choreography, per-shard stats
    frames, fault counters) into the cell dir for CI artifact upload.
    """
    import threading as _threading

    import numpy as np

    sys.path.insert(0, "tests")
    from test_chaos import _round_cfg  # noqa: E402

    from split_learning_tpu.broker import spawn_shard
    from split_learning_tpu.runtime.bus import (
        broker_stats, collect_broker_stats, find_port_block, shard_for,
    )
    from split_learning_tpu.runtime.chaos import make_runtime_transport
    from split_learning_tpu.runtime.client import ProtocolClient
    from split_learning_tpu.runtime.server import ProtocolServer

    cell_dir = pathlib.Path(tmp) / "broker_shard"
    cell_dir.mkdir(parents=True, exist_ok=True)
    shards = 2

    def spawn_plane():
        base = find_port_block(shards)
        procs = {i: spawn_shard("127.0.0.1", base + i, shard_index=i,
                                python_only=True)
                 for i in range(shards)}
        deadline = time.monotonic() + 120
        for i in range(shards):
            while time.monotonic() < deadline:
                try:
                    broker_stats("127.0.0.1", base + i, timeout=1.0)
                    break
                except Exception:  # noqa: BLE001 — still booting
                    time.sleep(0.25)
        return base, procs

    def run_round(tag, base, chaos_on):
        over = dict(
            global_rounds=3,   # enough round time for a MID-round kill
            transport={"kind": "tcp", "host": "127.0.0.1",
                       "port": base, "reliable": True,
                       # reply_* upgraded too: a kill landing on the
                       # START fan-out must be repaired by redelivery,
                       # not by waiting out the ready barrier (the
                       # README failure-model table's documented
                       # upgrade for control frames)
                       "reliable_queues": [
                           "intermediate_queue*", "gradient_queue*",
                           "rpc_queue", "aggregate_queue*",
                           "reply_*"],
                       "async_send": False},
            broker={"shards": shards})
        if chaos_on:
            over["chaos"] = {"enabled": True, "seed": seed,
                             "drop": 0.05, "duplicate": 0.1,
                             "reorder": 0.1}
        cfg = _round_cfg(pathlib_tmp, cell_dir / tag, **over)
        fc = FaultCounters()
        server = ProtocolServer(
            cfg, transport=make_runtime_transport(cfg, "server",
                                                  faults=fc),
            client_timeout=300.0)
        threads = []
        for stage, count in enumerate(cfg.clients, start=1):
            for i in range(count):
                cid = f"client_{stage}_{i}"
                client = ProtocolClient(
                    cfg, cid, stage,
                    transport=make_runtime_transport(cfg, cid,
                                                     faults=fc))
                th = _threading.Thread(target=client.run, daemon=True)
                th.start()
                threads.append(th)
        t0 = time.monotonic()
        res = server.serve()
        wall = time.monotonic() - t0
        for th in threads:
            th.join(timeout=30)
        return res, fc, wall

    pathlib_tmp = pathlib.Path(tmp)
    # fault-free twin on its own fresh plane
    base_b, procs_b = spawn_plane()
    try:
        res_base, _, _ = run_round("twin", base_b, chaos_on=False)
    finally:
        for p in procs_b.values():
            p.kill()
    if not res_base.history or not res_base.history[0].ok:
        return False, "fault-free twin round not ok"

    # chaotic run: drop/dup/reorder + mid-round shard SIGKILL+respawn
    base, procs = spawn_plane()
    victim = shard_for("intermediate_queue_0_0", shards)
    kill_info: dict = {}

    def killer():
        deadline = time.monotonic() + 200
        # prefer killing while frames sit queued-but-unconsumed (their
        # loss is what redelivery must repair); parked-GET delivery
        # bypasses the store, so depth>=1 is intermittent — past the
        # soft deadline a busy victim is killed regardless (the drop
        # chaos keeps the redelivery assertion independent).  The
        # trigger threshold is LOW and the poll tight: a warm-cache
        # round is sub-second, and a kill that waits too long lands in
        # the teardown instead of the round.
        soft = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                s = broker_stats("127.0.0.1", base + victim,
                                 timeout=1.0)
            except Exception:  # noqa: BLE001 — booting / mid-kill
                time.sleep(0.1)
                continue
            if s.get("published", 0) >= 4 and (
                    s.get("depth", 0) >= 1
                    or time.monotonic() >= soft
                    or s.get("published", 0) >= 12):
                procs[victim].kill()   # SIGKILL: queued frames die
                procs[victim].wait()
                kill_info["published_at_kill"] = s["published"]
                kill_info["depth_at_kill"] = s["depth"]
                kill_info["t_kill"] = time.monotonic()
                time.sleep(1.0)        # real downtime window
                procs[victim] = spawn_shard(
                    "127.0.0.1", base + victim, shard_index=victim,
                    python_only=True)
                kill_info["downtime_s"] = round(
                    time.monotonic() - kill_info["t_kill"], 3)
                return
            time.sleep(0.01)

    kt = _threading.Thread(target=killer, daemon=True)
    kt.start()
    try:
        res, fc, wall = run_round("chaos", base, chaos_on=True)
        kt.join(timeout=10)
        stats = collect_broker_stats("127.0.0.1", base, shards)
    finally:
        for p in procs.values():
            p.kill()
    snap = fc.snapshot()
    out = {
        "shards": shards, "base_port": base, "victim_shard": victim,
        "kill": {k: v for k, v in kill_info.items() if k != "t_kill"},
        "wall_s": round(wall, 3),
        "faults": snap,
        "shard_stats": stats,
    }
    (cell_dir / "broker_shard.json").write_text(
        json.dumps(out, indent=2, default=str))
    if not res.history or not all(r.ok for r in res.history):
        return False, "round not ok"
    if wall > 240:
        return False, f"round stalled ({wall:.0f}s)"
    if "published_at_kill" not in kill_info:
        return False, "victim shard never qualified for the kill " \
                      "(no mid-round traffic observed)"
    if snap.get("reconnects", 0) < 1:
        return False, f"no reconnects counted: {snap}"
    if snap.get("redeliveries", 0) < 1:
        return False, f"no redeliveries counted: {snap}"
    if snap.get("lost", 0) != 0:
        return False, f"phantom lost: {snap.get('lost')}"
    if snap.get("gave_up", 0) != 0:
        return False, f"redelivery gave up: {snap.get('gave_up')}"
    if [r.num_samples for r in res.history] \
            != [r.num_samples for r in res_base.history]:
        return False, "sample count drifted"
    import jax
    la = jax.tree_util.tree_leaves(res_base.params)
    lb = jax.tree_util.tree_leaves(res.params)
    if len(la) != len(lb) or any(
            np.asarray(a).tobytes() != np.asarray(b).tobytes()
            for a, b in zip(la, lb)):
        return False, "aggregation not bit-identical to the twin"
    return True, (f"shard {victim} killed+respawned "
                  f"(depth {kill_info.get('depth_at_kill')} at kill), "
                  f"{snap.get('reconnects')} reconnects "
                  f"{snap.get('redeliveries')} redeliveries "
                  f"{snap.get('dedup_hits', 0)} dedups, 0 lost "
                  f"[{wall:.0f}s]")


def mpmd_cell(tmp: str) -> tuple[bool, str]:
    """Cross-host MPMD stage-pipeline chaos cell (pipeline.remote): a
    3-stage deterministic round whose two later stages run on TWO
    server-spawned StageHost subprocesses over a real 2-shard TCP
    broker plane — and the stage host owning the stage-2 slot is
    SIGKILLed the moment the round attempt arms the stage watch
    (mid-round by construction).  PASSes iff

    * the round completes via the counted slot re-assignment (the
      dead host's slot moves to the survivor UNDER THE SAME client
      id, the attempt re-runs behind a bumped generation fence);
    * aggregation is BIT-IDENTICAL to a fault-free single-process
      twin (same client ids -> same per-client seeds -> same fold);
    * the fallback counts are exact: ``stage_host_deaths == 1`` and
      ``stage_reassigns == 1`` (one slot moved), and the survivor
      ends the round owning the victim's slot.

    Writes ``mpmd.json`` (assignment choreography, kill timing, fault
    counters) into the cell dir; the stage hosts' own log/metrics
    sidecars land under the cell's log dirs for CI artifact upload.
    """
    import threading as _threading

    import numpy as np

    sys.path.insert(0, "tests")
    from test_chaos import _round_cfg  # noqa: E402

    from split_learning_tpu.broker import spawn_shard
    from split_learning_tpu.runtime.bus import (
        broker_stats, find_port_block, ShardedTcpTransport,
    )
    from split_learning_tpu.runtime.client import ProtocolClient
    from split_learning_tpu.runtime.server import ProtocolServer

    cell_dir = pathlib.Path(tmp) / "mpmd"
    cell_dir.mkdir(parents=True, exist_ok=True)
    shards = 2

    def spawn_plane():
        base = find_port_block(shards)
        procs = [spawn_shard("127.0.0.1", base + i, shard_index=i,
                             python_only=True)
                 for i in range(shards)]
        deadline = time.monotonic() + 120
        for i in range(shards):
            while time.monotonic() < deadline:
                try:
                    broker_stats("127.0.0.1", base + i, timeout=1.0)
                    break
                except Exception:  # noqa: BLE001 — still booting
                    time.sleep(0.25)
        return base, procs

    def run_round(tag, base, n_hosts):
        """(result, ctx, wall, killed) — stage-1 feeders as threads;
        later stages in-process (n_hosts=0, the twin) or on spawned
        stage hosts, with the scripted mid-round SIGKILL when hosts
        are in play."""
        over = dict(
            clients=[2, 1, 1],
            topology={"cut_layers": [2, 4]},
            # dropout OFF: the middle stage relays activations on
            # receipt (arrival order), so with >= 3 stages the
            # bit-identity recipe additionally needs rng-insensitive
            # forwards — the 2-stage recipe's strict-SDA head never
            # exposed the middle relay's rng-to-batch assignment race
            model_kwargs={"dropout_rate": 0.0},
            transport={"kind": "tcp", "host": "127.0.0.1",
                       "port": base, "async_send": False},
            broker={"shards": shards})
        if n_hosts:
            over["pipeline"] = {"remote": True, "hosts": n_hosts,
                                "retries": 2}
        cfg = _round_cfg(pathlib.Path(tmp), cell_dir / tag, **over)
        server = ProtocolServer(
            cfg, transport=ShardedTcpTransport("127.0.0.1", base,
                                               shards),
            client_timeout=300.0)
        ctx = server.ctx
        threads = []
        stages = range(1, 2) if n_hosts else range(1, 4)
        for stage in stages:
            for i in range(cfg.clients[stage - 1]):
                cid = f"client_{stage}_{i}"
                client = ProtocolClient(
                    cfg, cid, stage,
                    transport=ShardedTcpTransport("127.0.0.1", base,
                                                  shards))
                th = _threading.Thread(target=client.run, daemon=True)
                th.start()
                threads.append(th)
        killed: list = []
        if n_hosts:
            def killer():
                deadline = time.monotonic() + 200
                while time.monotonic() < deadline:
                    # the stage watch arms exactly while a round
                    # attempt is in flight — a kill here is mid-round
                    # by construction, after the barrier committed to
                    # the standing assignment
                    if ctx._stage_watch:
                        hid = next(
                            (h for h in sorted(ctx._stage_assignments)
                             if ctx._stage_assignments[h]), None)
                        if hid:
                            slots = [
                                s["client_id"] for s in
                                ctx._stage_assignments[hid]]
                            proc = (ctx._stage_hosts.get(hid)
                                    or {}).get("proc")
                            if proc is not None:
                                proc.kill()   # SIGKILL
                                killed.append(
                                    {"host": hid, "slots": slots,
                                     "t": round(time.monotonic(), 3)})
                                return
                    time.sleep(0.005)
            kt = _threading.Thread(target=killer, daemon=True)
            kt.start()
        t0 = time.monotonic()
        res = server.serve()
        wall = time.monotonic() - t0
        for th in threads:
            th.join(timeout=30)
        return res, ctx, wall, (killed[0] if killed else None)

    # fault-free single-process twin on its own fresh plane
    base_b, procs_b = spawn_plane()
    try:
        res_base, _, _, _ = run_round("twin", base_b, 0)
    finally:
        for p in procs_b:
            p.kill()
    if not res_base.history or not res_base.history[0].ok:
        return False, "fault-free twin round not ok"

    # MPMD run: 2 stage hosts, scripted mid-round SIGKILL
    base, procs = spawn_plane()
    try:
        res, ctx2, wall, killed = run_round("chaos", base, 2)
    finally:
        for p in procs:
            p.kill()
    snap = ctx2.faults.snapshot()
    out = {
        "shards": shards, "base_port": base, "hosts": 2,
        "wall_s": round(wall, 3),
        "kill": killed,
        "final_assignments": {
            h: [s["client_id"] for s in sl]
            for h, sl in ctx2._stage_assignments.items()},
        "faults": snap,
    }
    (cell_dir / "mpmd.json").write_text(
        json.dumps(out, indent=2, default=str))
    if killed is None:
        return False, "no stage host qualified for the kill"
    if not res.history or not res.history[0].ok:
        return False, "round not ok after stage-host kill"
    if wall > 240:
        return False, f"round stalled ({wall:.0f}s)"
    if snap.get("stage_host_deaths") != 1:
        return False, f"deaths != 1: {snap}"
    if snap.get("stage_reassigns") != len(killed["slots"]):
        return False, f"reassigns != {len(killed['slots'])}: {snap}"
    moved = killed["slots"]
    survivor_slots = [
        cid for h, sl in ctx2._stage_assignments.items()
        if h != killed["host"] for cid in
        [s["client_id"] for s in sl]]
    if not all(cid in survivor_slots for cid in moved):
        return False, (f"moved slots {moved} not on a survivor: "
                       f"{out['final_assignments']}")
    if [r.num_samples for r in res.history] \
            != [r.num_samples for r in res_base.history]:
        return False, "sample count drifted"
    import jax
    la = jax.tree_util.tree_leaves(res_base.params)
    lb = jax.tree_util.tree_leaves(res.params)
    if len(la) != len(lb) or any(
            np.asarray(a).tobytes() != np.asarray(b).tobytes()
            for a, b in zip(la, lb)):
        return False, "aggregation not bit-identical to the twin"
    return True, (f"host {killed['host']} SIGKILLed mid-round, "
                  f"slot(s) {moved} re-assigned, fold bit-identical "
                  f"(1 death, {len(moved)} reassign) [{wall:.0f}s]")


def postmortem_cell(tmp: str) -> tuple[bool, str]:
    """Flight-recorder / postmortem chaos cell (runtime/blackbox.py +
    tools/sl_postmortem.py): the mpmd choreography — a 3-stage round
    with the later stages on 2 server-spawned StageHost subprocesses
    over a real 2-shard TCP broker plane — with the blackbox recorder
    armed in EVERY process, and the stage host owning a slot SIGKILLed
    mid-round.  SIGKILL is the oracle: the victim writes nothing, so
    the verdict can only come from the surviving fleet's dumps (the
    server's ring records the death with role + round, the fan-out
    snapshots the survivors, the broker sweep pulls the shard rings).
    PASSes iff

    * the round still completes via the counted slot re-assignment;
    * ``sl_postmortem`` over the cell's dumps names the KILLED host as
      the victim, role ``stage_host``, first abnormal event
      ``child_exit``/``participant_lost`` in the round that was in
      flight, reported by the server;
    * a fault-free twin of the same round, same recorder armed, yields
      the clean "no abnormal termination" verdict.

    Writes ``postmortem.json`` + ``postmortem_twin.json`` and the raw
    ``blackbox-*.json`` dumps into the cell dir for CI upload.
    """
    import threading as _threading

    sys.path.insert(0, "tests")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
    import sl_postmortem  # noqa: E402
    from test_chaos import _round_cfg  # noqa: E402

    from split_learning_tpu.broker import spawn_shard
    from split_learning_tpu.runtime import blackbox
    from split_learning_tpu.runtime.bus import (
        broker_stats, find_port_block, ShardedTcpTransport,
    )
    from split_learning_tpu.runtime.client import ProtocolClient
    from split_learning_tpu.runtime.server import ProtocolServer

    cell_dir = pathlib.Path(tmp) / "postmortem"
    cell_dir.mkdir(parents=True, exist_ok=True)
    shards = 2

    def spawn_plane():
        base = find_port_block(shards)
        procs = [spawn_shard("127.0.0.1", base + i, shard_index=i,
                             python_only=True)
                 for i in range(shards)]
        deadline = time.monotonic() + 120
        for i in range(shards):
            while time.monotonic() < deadline:
                try:
                    broker_stats("127.0.0.1", base + i, timeout=1.0)
                    break
                except Exception:  # noqa: BLE001 — still booting
                    time.sleep(0.25)
        return base, procs

    def run_round(tag, base, n_hosts):
        dump_dir = cell_dir / tag
        dump_dir.mkdir(parents=True, exist_ok=True)
        over = dict(
            clients=[2, 1, 1],
            topology={"cut_layers": [2, 4]},
            model_kwargs={"dropout_rate": 0.0},
            transport={"kind": "tcp", "host": "127.0.0.1",
                       "port": base, "async_send": False},
            broker={"shards": shards},
            observability={"blackbox": {
                "enabled": True, "dump_dir": str(dump_dir)}})
        if n_hosts:
            over["pipeline"] = {"remote": True, "hosts": n_hosts,
                                "retries": 2}
        cfg = _round_cfg(pathlib.Path(tmp), dump_dir / "logs", **over)
        # arm THIS process's recorder as the server role (spawned
        # stage hosts arm themselves from the same config in main())
        blackbox._reset_for_tests()
        blackbox.configure(cfg, "server", role="server")
        server = ProtocolServer(
            cfg, transport=ShardedTcpTransport("127.0.0.1", base,
                                               shards),
            client_timeout=300.0)
        ctx = server.ctx
        threads = []
        stages = range(1, 2) if n_hosts else range(1, 4)
        for stage in stages:
            for i in range(cfg.clients[stage - 1]):
                cid = f"client_{stage}_{i}"
                client = ProtocolClient(
                    cfg, cid, stage,
                    transport=ShardedTcpTransport("127.0.0.1", base,
                                                  shards))
                th = _threading.Thread(target=client.run, daemon=True)
                th.start()
                threads.append(th)
        killed: list = []
        if n_hosts:
            def killer():
                deadline = time.monotonic() + 200
                while time.monotonic() < deadline:
                    if ctx._stage_watch:
                        hid = next(
                            (h for h in sorted(ctx._stage_assignments)
                             if ctx._stage_assignments[h]), None)
                        if hid:
                            proc = (ctx._stage_hosts.get(hid)
                                    or {}).get("proc")
                            if proc is not None:
                                rnd = getattr(ctx, "_cur_round", 0)
                                proc.kill()   # SIGKILL: writes NOTHING
                                killed.append({"host": hid,
                                               "round": rnd})
                                return
                    time.sleep(0.005)
            kt = _threading.Thread(target=killer, daemon=True)
            kt.start()
        res = server.serve()
        for th in threads:
            th.join(timeout=30)
        # give the fire-and-forget broker blackbox sweep a beat to
        # land its shard dumps before the assembler scans the dir
        time.sleep(1.5)
        blackbox.dump("cell_end")
        return res, (killed[0] if killed else None), dump_dir

    # fault-free twin: same recorder armed, nothing dies -> the
    # assembler must come back CLEAN (the no-false-positive half)
    base_b, procs_b = spawn_plane()
    try:
        res_twin, _, twin_dir = run_round("twin", base_b, 0)
    finally:
        for p in procs_b:
            p.kill()
    if not res_twin.history or not res_twin.history[0].ok:
        return False, "fault-free twin round not ok"
    doc_twin = sl_postmortem.assemble(twin_dir)
    (cell_dir / "postmortem_twin.json").write_text(
        json.dumps(doc_twin, indent=2, default=str))
    if doc_twin["verdict"]["abnormal"]:
        return False, (f"twin verdict not clean: "
                       f"{doc_twin['verdict']}")

    # chaos run: 2 stage hosts, one SIGKILLed mid-round
    base, procs = spawn_plane()
    try:
        res, killed, chaos_dir = run_round("chaos", base, 2)
    finally:
        for p in procs:
            p.kill()
    if killed is None:
        return False, "no stage host qualified for the kill"
    if not res.history or not res.history[0].ok:
        return False, "round not ok after stage-host kill"
    doc = sl_postmortem.assemble(chaos_dir)
    (cell_dir / "postmortem.json").write_text(
        json.dumps(doc, indent=2, default=str))
    v = doc["verdict"]
    if not v["abnormal"]:
        return False, "kill not detected: verdict came back clean"
    if v["victim"] != killed["host"]:
        return False, (f"victim {v['victim']} != killed "
                       f"{killed['host']}")
    if v["role"] != "stage_host":
        return False, f"role {v['role']} != stage_host"
    if v["cause"]["kind"] not in ("child_exit", "participant_lost"):
        return False, f"cause {v['cause']['kind']} unexpected"
    if v["round"] != killed["round"]:
        return False, (f"round {v['round']} != in-flight "
                       f"{killed['round']}")
    if v["reported_by"] != "server":
        return False, f"reported by {v['reported_by']}, not server"
    if len(doc["dumps"]) < 2:
        return False, f"only {len(doc['dumps'])} dump(s) collected"
    print(sl_postmortem.render(doc))
    return True, (f"{killed['host']} SIGKILLed mid-round; verdict "
                  f"names it ({v['role']}, {v['cause']['kind']}, "
                  f"round {v['round']}) from {len(doc['dumps'])} "
                  f"survivor dumps; twin clean")


def kernels_cell(tmp: str, seed: int = 19) -> tuple[bool, str]:
    """Pallas kernel-plane chaos cell (kernels.*): a 3-client round
    with the FULL wire compression stack AND every fused kernel
    enabled (``kernels: {quantize, dequantize, stage_update}``, the
    sharded mesh update backend underneath), under drop + duplicate +
    delay injection with the reliable layer masking.  PASSes iff

    * the round completes without a barrier stall;
    * the aggregated params are BIT-IDENTICAL to a fault-free,
      KERNELS-OFF twin on the same codec stack: the single-pass Pallas
      kernels must be invisible to training semantics — same codes,
      same scales, same fused update, down to the last bit — even
      while chaos reorders the quantized wire around them (the live
      twin of the PK001 lowering gate and tests/test_kernels.py);
    * the kernel plan was actually installed for the run (the process
      plan the self-describing decode path follows).
    """
    import numpy as np

    sys.path.insert(0, "tests")
    from test_chaos import _chaos, _round_cfg, _run_cell  # noqa: E402

    from split_learning_tpu.ops import kernels as kplane

    kernels_on = {"quantize": True, "dequantize": True,
                  "stage_update": True}
    common = dict(transport={"codec": dict(CODEC_STACK)},
                  aggregation={"sharded": True})
    chaos = _chaos(seed=seed, drop=0.10, duplicate=0.10, delay=0.15,
                   delay_s=0.02)
    # fault-free kernels-off twin first (the plan default is all-off)
    cfg_b = _round_cfg(pathlib.Path(tmp),
                       pathlib.Path(tmp) / "kernels_base", **common)
    res_b = _run_cell(cfg_b)
    fc = FaultCounters()
    cfg_k = _round_cfg(pathlib.Path(tmp),
                       pathlib.Path(tmp) / "kernels_chaos",
                       kernels=kernels_on, **common)
    t0 = time.monotonic()
    res_k = _run_cell(cfg_k, chaos_cfg=chaos, reliable=True, faults=fc)
    wall = time.monotonic() - t0
    plan = kplane.plan()
    if not (plan.quantize and plan.dequantize and plan.stage_update):
        return False, f"kernel plan never installed: {plan}"
    if not (res_k.history and res_k.history[0].ok
            and res_b.history and res_b.history[0].ok):
        return False, "round not ok"
    if wall > 240:
        return False, f"barrier stall ({wall:.0f}s)"
    if res_k.history[0].num_samples != res_b.history[0].num_samples:
        return False, "sample count drifted"
    import jax
    la = jax.tree_util.tree_leaves(res_b.params)
    lb = jax.tree_util.tree_leaves(res_k.params)
    if len(la) != len(lb) or any(
            np.asarray(a).tobytes() != np.asarray(b).tobytes()
            for a, b in zip(la, lb)):
        return False, "kernels+chaos fold not bit-identical"
    snap = fc.snapshot()
    injected = sum(snap.get(k, 0) for k in ("drops", "duplicates",
                                            "delays"))
    if not injected:
        return False, "chaos injected nothing"
    return True, (f"bit-identical with all kernels on "
                  f"({injected} faults injected, {wall:.0f}s)")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Sweep fault probabilities over seeds; print a "
                    "pass/fail matrix.")
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--seed-base", type=int, default=100)
    ap.add_argument("--messages", type=int, default=150)
    ap.add_argument("--probs", default="0.05,0.2,0.4",
                    help="comma-separated probabilities")
    ap.add_argument("--only", default=None,
                    help="restrict to one cell, e.g. drop:0.4")
    ap.add_argument("--full", action="store_true",
                    help="full tiny training round per cell (slow)")
    ap.add_argument("--codec", action="store_true",
                    help="with --full: run cells with the wire "
                         "compression stack (int8 activations + top-k "
                         "EF gradients + delta Updates) — proves the "
                         "codec state deterministic under faults")
    ap.add_argument("--artifacts-dir", default=None,
                    help="with --full: run cells under this directory "
                         "so spans-*.jsonl / metrics.jsonl / "
                         "trace.json survive for CI artifact upload")
    ap.add_argument("--fleet", action="store_true",
                    help="run ONLY the live-telemetry fleet cell: a "
                         "3-client round with one rpc-delayed client; "
                         "asserts the FleetMonitor flags it, /metrics "
                         "lints mid-round, and sl_top renders the "
                         "/fleet snapshot (writes fleet.json)")
    ap.add_argument("--async", dest="async_mode", action="store_true",
                    help="run ONLY the async-mode cell: a 3-client "
                         "aux-loss round under delay+drop+dup must "
                         "complete with no barrier stall, fold "
                         "deterministically (twin-seed bit-identity), "
                         "and count stale rejections exactly")
    ap.add_argument("--tree-remote", dest="tree_remote",
                    action="store_true",
                    help="run ONLY the multi-process aggregator-tree "
                         "cell: 3 aggregator subprocesses over a real "
                         "TCP broker serve a 3-client round's tree; "
                         "one is SIGKILLed mid-round and the round "
                         "must complete via the counted direct-to-"
                         "root fallback drain")
    ap.add_argument("--sched", dest="sched_mode",
                    action="store_true",
                    help="run ONLY the closed-loop scheduler cell: a "
                         "heterogeneous 6-client synthetic round with "
                         "one compute- and one wire-straggler under "
                         "rpc dup+reorder chaos; both must be "
                         "attributed correctly and demoted with their "
                         "knobs retuned, the round must complete, and "
                         "the kind=sched decisions journal must "
                         "validate (writes sched.json)")
    ap.add_argument("--fleet-scale", dest="fleet_scale",
                    action="store_true",
                    help="run ONLY the hierarchical digest roll-up "
                         "cell: 24 synthetic clients' heartbeats roll "
                         "up through 2 aggregator-node digest workers "
                         "under dup+reorder chaos; one node is killed "
                         "and its clients must fall back to direct "
                         "heartbeats, counted, with no phantom lost "
                         "flap (writes fleet_digest.json)")
    ap.add_argument("--broker-shard", dest="broker_shard",
                    action="store_true",
                    help="run ONLY the sharded broker plane cell: a "
                         "3-client round over 2 real broker shard "
                         "processes with drop+dup+reorder chaos; the "
                         "data-plane shard is SIGKILLed mid-round and "
                         "respawned, and the round must complete "
                         "bit-identical to a fault-free twin with "
                         "exact fault counts (reconnects/redeliveries "
                         "counted, zero lost) — writes "
                         "broker_shard.json")
    ap.add_argument("--mpmd", dest="mpmd_mode", action="store_true",
                    help="run ONLY the cross-host MPMD stage-pipeline "
                         "cell: a 3-stage round with the later stages "
                         "on 2 spawned StageHost subprocesses over a "
                         "real 2-shard TCP broker; one stage host is "
                         "SIGKILLed mid-round and the round must "
                         "complete via the counted slot re-assignment, "
                         "bit-identical to a fault-free single-process "
                         "twin (writes mpmd.json)")
    ap.add_argument("--postmortem", dest="postmortem_mode",
                    action="store_true",
                    help="run ONLY the flight-recorder cell: the mpmd "
                         "choreography with the blackbox recorder "
                         "armed fleet-wide; a stage host is SIGKILLed "
                         "mid-round and sl_postmortem over the "
                         "surviving dumps must name the killed host, "
                         "its role and the in-flight round, while a "
                         "fault-free twin's report comes back clean "
                         "(writes postmortem.json + blackbox-*.json)")
    ap.add_argument("--kernels", dest="kernels_mode",
                    action="store_true",
                    help="run ONLY the Pallas kernel-plane cell: a "
                         "3-client round with the full codec stack and "
                         "every fused kernel enabled (quantize/"
                         "dequantize/stage_update over the sharded "
                         "mesh backend) under drop+dup+delay must stay "
                         "bit-identical to a fault-free kernels-off "
                         "twin on the same stack")
    ap.add_argument("--overlap", dest="overlap_mode",
                    action="store_true",
                    help="run ONLY the sync-overlap cell: a 3-client "
                         "sync round with learning.sync-overlap on "
                         "under drop+dup+delay must stay bit-identical "
                         "to a fault-free overlap-off twin with no "
                         "barrier stall")
    args = ap.parse_args(argv)

    if args.tree_remote:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_tree_remote_")
        t0 = time.monotonic()
        ok, note = tree_remote_cell(tmp)
        dt = time.monotonic() - t0
        print(f"tree-remote cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.mpmd_mode:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_mpmd_")
        t0 = time.monotonic()
        ok, note = mpmd_cell(tmp)
        dt = time.monotonic() - t0
        print(f"mpmd cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.postmortem_mode:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_postmortem_")
        t0 = time.monotonic()
        ok, note = postmortem_cell(tmp)
        dt = time.monotonic() - t0
        print(f"postmortem cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.broker_shard:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_broker_shard_")
        t0 = time.monotonic()
        ok, note = broker_shard_cell(tmp)
        dt = time.monotonic() - t0
        print(f"broker-shard cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.fleet_scale:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_fleet_scale_")
        t0 = time.monotonic()
        ok, note = fleet_scale_cell(tmp)
        dt = time.monotonic() - t0
        print(f"fleet-scale cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.sched_mode:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_sched_")
        t0 = time.monotonic()
        ok, note = sched_cell(tmp)
        dt = time.monotonic() - t0
        print(f"sched cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.kernels_mode:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_kernels_")
        t0 = time.monotonic()
        ok, note = kernels_cell(tmp)
        dt = time.monotonic() - t0
        print(f"kernels cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.overlap_mode:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_overlap_")
        t0 = time.monotonic()
        ok, note = overlap_cell(tmp)
        dt = time.monotonic() - t0
        print(f"overlap cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.async_mode:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_async_")
        t0 = time.monotonic()
        ok, note = async_cell(tmp)
        dt = time.monotonic() - t0
        print(f"async cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    if args.fleet:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_fleet_")
        t0 = time.monotonic()
        ok, note = fleet_cell(tmp)
        dt = time.monotonic() - t0
        print(f"fleet cell: {'PASS' if ok else 'FAIL'} ({note}) "
              f"[{dt:.1f}s, artifacts in {tmp}]")
        return 0 if ok else 1

    faults = ["drop", "duplicate", "reorder", "corrupt", "delay",
              "mixed"]
    probs = [float(p) for p in args.probs.split(",")]
    cells = [(f, p) for f in faults for p in probs]
    if args.only:
        f, _, p = args.only.partition(":")
        cells = [(f, float(p))]

    tmp = None
    if args.full:
        if args.artifacts_dir:
            tmp = args.artifacts_dir
            pathlib.Path(tmp).mkdir(parents=True, exist_ok=True)
        else:
            import tempfile
            tmp = tempfile.mkdtemp(prefix="chaos_sweep_")

    width = max(len(f) for f, _ in cells) + 6
    print(f"{'cell':<{width}} " + " ".join(
        f"seed{args.seed_base + i:<4}" for i in range(args.seeds)))
    failures = 0
    for fault, prob in cells:
        row = []
        for i in range(args.seeds):
            seed = args.seed_base + i
            t0 = time.monotonic()
            if args.full:
                ok, note = full_round_cell(fault, prob, seed, tmp,
                                           codec=args.codec)
            else:
                ok, note = transport_cell(fault, prob, seed,
                                          args.messages)
            dt = time.monotonic() - t0
            row.append("PASS" if ok else f"FAIL({note})")
            if not ok:
                failures += 1
                print(f"  FAIL {fault}:{prob} seed={seed} -> {note} "
                      f"({dt:.1f}s)", file=sys.stderr)
        print(f"{fault + ':' + str(prob):<{width}} " + " ".join(
            f"{r:<8}" for r in row))
    print(f"\n{len(cells) * args.seeds - failures}/"
          f"{len(cells) * args.seeds} cells passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
