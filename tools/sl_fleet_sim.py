#!/usr/bin/env python
"""``sl_fleet_sim`` — fleet-scale control-plane simulator / load
generator.

Registers 1k–10k heterogeneous synthetic clients
(``runtime/simfleet.py``: configurable compute/wire speed
distributions, membership churn, registration storms) against the REAL
server, aggregation and telemetry planes over an in-proc transport,
and runs full protocol rounds.  This is the closed-loop scheduler's
proof rig — and the load generator for any control-plane scale
question (how long does a 10k registration storm take? does the
scheduler's decision pass stay flat per client?).

    # 1k clients, 3 rounds, scheduler on, one compute- and one
    # wire-straggler per 100
    python tools/sl_fleet_sim.py --clients 1000 --rounds 3 --sched \
        --compute-slow 10 --wire-slow 10

    # paired scheduler-on/off comparison on the same fleet + seed
    python tools/sl_fleet_sim.py --clients 64 --rounds 4 --paired

Prints one JSON summary: per-round walls, fleet health counts,
scheduler decisions, and the decision-pass cost.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

# runnable from anywhere: the repo root precedes any installed copy
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def build_cfg(args, log_dir: str, sched: bool):
    from split_learning_tpu.config import from_dict
    return from_dict({
        "model": "KWT", "dataset": "SPEECHCOMMANDS",
        "clients": [args.clients, args.heads],
        "global_rounds": args.rounds,
        "synthetic_size": 48, "val_max_batches": 1,
        "val_batch_size": 16,
        "model_kwargs": {"embed_dim": 16, "num_heads": 2,
                         "mlp_dim": 32},
        "log_path": log_dir,
        "learning": {"batch_size": 4},
        "topology": {"cut_layers": [2],
                     "elastic_join": bool(args.churn)},
        "checkpoint": {"save": False, "validate": False,
                       "directory": f"{log_dir}/ckpt"},
        "observability": {
            "heartbeat_interval": args.heartbeat_interval,
            "liveness_timeout": max(30.0,
                                    8 * args.heartbeat_interval),
            # hierarchical roll-up (--digest N): clients' heartbeats
            # route through N aggregator-node digest workers instead
            # of landing individually on the server's rpc pump
            "digest_interval": (args.digest_interval
                                if args.digest else 0.0),
            "watchlist_size": args.watchlist,
            "http_port": (0 if args.http else None)},
        "scheduler": {"enabled": sched,
                      "warmup_rounds": 1,
                      "evict_after": args.evict_after,
                      "barrier_grace_s": args.grace},
    })


def run_leg(args, sched: bool, log_dir: str) -> dict:
    import threading

    from split_learning_tpu.runtime.bus import (
        Broker, InProcTransport, ShardedTcpTransport, find_port_block,
    )
    from split_learning_tpu.runtime.server import ProtocolServer
    from split_learning_tpu.runtime.simfleet import (
        SyntheticFleet, hetero_fleet,
    )

    cfg = build_cfg(args, log_dir, sched)
    specs = hetero_fleet(
        args.clients, args.heads,
        compute_speed=args.compute_speed,
        compute_slow=args.compute_slow,
        compute_slow_factor=args.compute_slow_factor,
        wire_slow=args.wire_slow, samples=args.samples,
        joiners=args.churn, join_delay_s=args.join_delay,
        leavers=args.churn, seed=args.seed)
    from split_learning_tpu.runtime.log import Logger
    # --shards N: host N in-proc event-loop broker shards and drive
    # the whole deployment over the REAL sharded TCP plane (the sim's
    # multi-driver mode routes every queue to its owning shard);
    # default stays the zero-wire in-proc transport
    brokers = []
    bus_factory = None
    if args.shards:
        base = find_port_block(args.shards)
        brokers = [Broker("127.0.0.1", base + i,
                          shard_id=f"shard_{i}")
                   for i in range(args.shards)]

        def bus_factory():
            return ShardedTcpTransport("127.0.0.1", base, args.shards)
        bus = bus_factory()
    else:
        bus = InProcTransport()
    # console off: stdout is this tool's JSON summary
    server = ProtocolServer(cfg, transport=bus,
                            logger=Logger.for_run(cfg, "server",
                                                  console=False),
                            client_timeout=args.client_timeout)
    # in-proc digest nodes (--digest N): the clients' heartbeats roll
    # up through these instead of hitting the server's rpc pump
    # individually — the 100k-tier telemetry path, driveable from
    # this CLI
    nodes, node_threads = [], []
    if args.digest:
        from split_learning_tpu.runtime.aggnode import AggregatorNode
        for i in range(args.digest):
            # over the sharded plane each node owns fresh connections
            # (a shared blocking get would serialize a shard socket)
            mk = bus_factory if bus_factory is not None \
                else (lambda: bus)
            n = AggregatorNode(cfg, f"tel_node_{i}", transport=mk(),
                               fold_transport=mk(),
                               digest_transport=mk())
            t = threading.Thread(target=n.run, daemon=True)
            t.start()
            nodes.append(n)
            node_threads.append(t)
    t_reg = time.monotonic()
    fleet = SyntheticFleet(
        bus, specs, heartbeat_interval=args.heartbeat_interval,
        time_scale=args.time_scale,
        codec_gain=args.codec_gain,
        drivers=args.drivers, bus_factory=bus_factory).start()
    t0 = time.monotonic()
    try:
        res = server.serve()
    finally:
        fleet.stop()
        for n in nodes:
            n.stop()
        for b in brokers:
            b.close()
    wall = time.monotonic() - t0
    out = {
        "sched": sched,
        "clients": args.clients, "rounds": args.rounds,
        "register_to_serve_s": round(t0 - t_reg, 3),
        "total_wall_s": round(wall, 3),
        "round_walls_s": [round(r.wall_s, 3) for r in res.history],
        "rounds_ok": all(r.ok for r in res.history),
        "sim_errors": fleet.errors[:5],
    }
    ctx = server.ctx
    if ctx.fleet is not None:
        snap = ctx.fleet.snapshot(series=False)
        out["fleet_counts"] = snap["counts"]
        if snap.get("digest"):
            out["digest"] = {
                "nodes": len(snap["digest"]["nodes"]),
                "clients": snap["digest"]["clients"],
                "quantiles": snap["digest"]["quantiles"],
                "watchlist": len(snap.get("watchlist") or []),
                "fallbacks": ctx.faults.snapshot().get(
                    "digest_fallbacks", 0)}
    if ctx.scheduler is not None:
        sch = ctx.scheduler
        out["decisions"] = [
            {k: d[k] for k in ("action", "round", "client", "why")}
            for d in sch.decisions if d["action"] != "decide"]
        out["decision_ms"] = (
            ctx.gauges.get("sched_decision_ms"))
        out["decision_ms_per_client"] = (
            round(out["decision_ms"] / max(args.clients, 1), 6)
            if out["decision_ms"] is not None else None)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fleet-scale control-plane simulator (synthetic "
                    "clients against the real server planes).")
    ap.add_argument("--clients", type=int, default=100,
                    help="stage-1 synthetic clients")
    ap.add_argument("--heads", type=int, default=1)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--samples", type=int, default=32,
                    help="samples per client per round")
    ap.add_argument("--compute-speed", type=float, default=100.0)
    ap.add_argument("--compute-slow", type=int, default=0,
                    help="clients at compute-speed / slow-factor")
    ap.add_argument("--compute-slow-factor", type=float, default=8.0)
    ap.add_argument("--wire-slow", type=int, default=0,
                    help="clients whose wire time ~= 6x compute")
    ap.add_argument("--churn", type=int, default=0,
                    help="late joiners AND early leavers (each)")
    ap.add_argument("--join-delay", type=float, default=2.0)
    ap.add_argument("--time-scale", type=float, default=1.0,
                    help="multiplier on every simulated duration")
    ap.add_argument("--heartbeat-interval", type=float, default=0.5)
    ap.add_argument("--codec-gain", type=float, default=4.0,
                    help="wire speedup a granted codec knob models")
    ap.add_argument("--grace", type=float, default=0.5,
                    help="scheduler.barrier-grace-s")
    ap.add_argument("--evict-after", type=int, default=2)
    ap.add_argument("--client-timeout", type=float, default=300.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sched", action="store_true",
                    help="enable the closed-loop scheduler")
    ap.add_argument("--paired", action="store_true",
                    help="run scheduler-off then scheduler-on on the "
                         "same fleet and report the wall ratio")
    ap.add_argument("--digest", type=int, default=0, metavar="N",
                    help="roll heartbeats up through N in-proc "
                         "aggregator-node digest workers "
                         "(observability.digest-interval) instead of "
                         "one frame per client on the rpc pump")
    ap.add_argument("--digest-interval", type=float, default=1.0)
    ap.add_argument("--watchlist", type=int, default=64,
                    help="observability.watchlist-size (digest mode)")
    ap.add_argument("--http", action="store_true",
                    help="serve /metrics + /fleet during the run")
    ap.add_argument("--shards", type=int, default=0, metavar="N",
                    help="host N in-proc broker shards and run the "
                         "deployment over the real sharded TCP plane "
                         "(broker.shards) instead of the in-proc bus")
    ap.add_argument("--drivers", type=int, default=1,
                    help="fleet driver threads; with --shards each "
                         "owns its own per-shard connections "
                         "(shard-affine client placement)")
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args(argv)

    import tempfile
    log_dir = args.log_dir or tempfile.mkdtemp(prefix="sl_fleet_sim_")
    if args.paired:
        off = run_leg(args, sched=False, log_dir=f"{log_dir}/off")
        on = run_leg(args, sched=True, log_dir=f"{log_dir}/on")
        steady_off = off["round_walls_s"][-1]
        steady_on = on["round_walls_s"][-1]
        out = {"off": off, "on": on,
               "sched_wall_ratio_vs_static":
                   round(steady_on / steady_off, 4)
                   if steady_off else None}
    else:
        out = run_leg(args, sched=args.sched, log_dir=log_dir)
    json.dump(out, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
