#!/usr/bin/env bash
# TPU flagship run, launched opportunistically by tools/tpu_watch.py
# when the tunnel gives a window: full baseline1 scale (50 rounds,
# 2500 samples/feeder/round) — minutes on the chip vs hours on CPU.
# Artifacts land in artifacts/flagship_tpu/ and are committed by the
# watcher loop's caller (or the end-of-round driver sweep).
set -euo pipefail
cd "$(dirname "$0")/.."
PYTHONPATH="$PWD:${PYTHONPATH:-}" timeout 7200 python tools/flagship.py \
  --rounds 50 --samples 2500 --synthetic-size 5000 \
  --out artifacts/flagship_tpu --tag tpu
git add artifacts/flagship_tpu/FLAGSHIP.json artifacts/flagship_tpu/metrics.jsonl 2>/dev/null || true
git commit -m "Record TPU flagship multi-round learning trajectory" \
  --only artifacts/flagship_tpu/FLAGSHIP.json artifacts/flagship_tpu/metrics.jsonl || true
