#!/usr/bin/env python
"""Assemble a fleet's flight-recorder dumps into a causal postmortem.

Every process in a deployment carries a blackbox ring
(``runtime/blackbox.py``) and dumps ``blackbox-{participant}.json`` on
abnormal exit or on the server's fleet-snapshot fan-out.  This tool
reads every dump under a run's artifacts directory (plus the span
journals and rotated ``metrics.jsonl`` when present), aligns the
per-process clocks on the wire's ``t_send`` edges, merges the rings
into one fleet timeline, and names the **proximate cause**: the first
abnormal event — a caught signal, an unhandled exception, a sticky
ChaosCrash, a ``participant_lost``/``child_exit`` the server recorded,
a dead broker shard — with its owner, the victim's role, the round it
died in, the frames in flight at that moment and the barrier the
server was stalled in.

A SIGKILL'd victim writes nothing; its death is named from the
*survivors'* evidence (the server's ``participant_lost``/``child_exit``
events carry the victim, role and round).  Torn or truncated dumps are
scavenge-parsed, never fatal.  A fault-free run yields a clean
"no abnormal termination" report — the chaos suite's fault-free twin
asserts exactly that.

    python tools/sl_postmortem.py <artifacts-dir>               # report
    python tools/sl_postmortem.py <artifacts-dir> -o postmortem.json
    python tools/sl_postmortem.py <artifacts-dir> --format json
"""

from __future__ import annotations

import argparse
import collections
import json
import pathlib
import sys

_REPO = pathlib.Path(__file__).resolve().parents[1]
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from split_learning_tpu.runtime.blackbox import (  # noqa: E402
    ABNORMAL_KINDS, find_dumps, load_dump,
)

#: the server's barrier wait spans in round order — a death mid-round
#: stalls the first of these the server never closed afterwards
BARRIER_ORDER = ("ready_wait", "notify_wait", "update_wait")

#: events this close (s) before the cause count as "in flight" when
#: their publish was never consumed
IN_FLIGHT_WINDOW_S = 30.0


# -- loading ----------------------------------------------------------------

def load_fleet(root: pathlib.Path) -> list[dict]:
    """Every parseable dump under ``root`` (scavenged ones flagged
    ``torn``); unreadable files are skipped, never fatal."""
    out = []
    for path in find_dumps(root):
        doc = load_dump(path)
        if doc is None:
            continue
        doc["_path"] = str(path)
        out.append(doc)
    return out


def load_spans(root: pathlib.Path) -> list[dict]:
    recs = []
    root = pathlib.Path(root)
    for path in sorted(set(root.rglob("spans-*.jsonl"))):
        try:
            text = path.read_text(errors="replace")
        except OSError:
            continue
        for line in text.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                recs.append(rec)
    return recs


def load_rounds(root: pathlib.Path) -> list[dict]:
    """kind=round records from metrics.jsonl + its rotated siblings
    (oldest first), for naming the last completed round."""
    out = []
    root = pathlib.Path(root)
    paths = []
    for p in root.rglob("metrics.jsonl*"):
        suffix = p.name.rsplit(".", 1)[-1]
        if p.name.endswith(".jsonl") or suffix.isdigit():
            paths.append(p)
    for p in sorted(set(paths)):
        try:
            text = p.read_text(errors="replace")
        except OSError:
            continue
        for line in text.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("kind") == "round":
                out.append(rec)
    return out


# -- clock alignment --------------------------------------------------------

def estimate_offsets(spans: list[dict],
                     reference: str = "server") -> dict[str, float]:
    """Per-participant clock offset (seconds to ADD to that clock to
    land on the reference's), from the wire's ``t_send`` edges.

    Every *consume* span carries ``rtt_ms`` = receiver wall clock
    minus the sender-stamped SLT2 ctx ``t_send``, and its ``parent``
    is the sender's publish span — so each edge measures
    ``latency + (C_receiver - C_sender)``.  With traffic in BOTH
    directions between two processes the latency cancels:
    ``C_r - C_s = (min d_sr - min d_rs) / 2``.  Offsets propagate
    breadth-first from the reference; unreached participants get 0
    (same host, same clock — the common case)."""
    owner: dict[str, str] = {}
    for r in spans:
        sid = r.get("span")
        if sid:
            owner[sid] = r.get("part", "?")
    pair_min: dict[tuple, float] = {}
    for r in spans:
        if r.get("name") != "consume" or r.get("rtt_ms") is None:
            continue
        sender = owner.get(r.get("parent") or "")
        receiver = r.get("part")
        if not sender or not receiver or sender == receiver:
            continue
        d = float(r["rtt_ms"]) / 1e3
        key = (sender, receiver)
        pair_min[key] = min(pair_min.get(key, d), d)
    # C_r - C_s per bidirectional pair
    skew: dict[tuple, float] = {}
    for (s, r), d_sr in pair_min.items():
        d_rs = pair_min.get((r, s))
        if d_rs is not None:
            skew[(s, r)] = (d_sr - d_rs) / 2.0
    offsets = {reference: 0.0}
    frontier = [reference]
    while frontier:
        nxt = []
        for a in frontier:
            for (s, r), sk in skew.items():
                if s == a and r not in offsets:
                    # C_r = C_s + sk -> shift r by offset(s) - sk
                    offsets[r] = offsets[s] - sk
                    nxt.append(r)
                elif r == a and s not in offsets:
                    offsets[s] = offsets[r] + sk
                    nxt.append(s)
        frontier = nxt
    return offsets


# -- timeline ---------------------------------------------------------------

#: tie-break severity at equal timestamps: earlier in ABNORMAL_KINDS
#: wins (a caught signal beats the lost-transition it caused)
_SEVERITY = {k: i for i, k in enumerate(ABNORMAL_KINDS)}


def build_timeline(dumps: list[dict],
                   offsets: dict[str, float]) -> list[dict]:
    """All rings merged, clock-aligned, oldest first.  Each event is
    annotated with its ``owner`` (the process whose ring recorded it)
    and the owner's ``role``."""
    events = []
    for doc in dumps:
        own = str(doc.get("participant", "?"))
        role = str(doc.get("role", "?"))
        off = offsets.get(own, 0.0)
        for ev in doc.get("events", []):
            if not isinstance(ev, dict) or "t" not in ev:
                continue
            e = dict(ev)
            e["owner"] = own
            e["owner_role"] = role
            e["t_aligned"] = float(ev["t"]) + off
            events.append(e)
    events.sort(key=lambda e: (e["t_aligned"],
                               _SEVERITY.get(e.get("kind"), 99)))
    return events


def find_cause(timeline: list[dict]) -> dict | None:
    """The FIRST abnormal event on the aligned fleet timeline — the
    proximate cause every later abnormality cascades from."""
    for ev in timeline:
        if ev.get("kind") in ABNORMAL_KINDS:
            return ev
    return None


def _victim_of(cause: dict) -> tuple[str, str]:
    """(victim participant, victim role).  Server-recorded deaths name
    the victim in the event; a signal/exception/chaos_crash IS the
    recording process's own death."""
    kind = cause.get("kind")
    if kind in ("participant_lost", "child_exit"):
        return (str(cause.get("participant", "?")),
                str(cause.get("role", "?")))
    if kind == "shard_dead":
        return (f"broker-shard_{cause.get('shard', '?')}",
                "broker_shard")
    return (str(cause.get("owner", "?")),
            str(cause.get("owner_role", "?")))


def in_flight_frames(timeline: list[dict], t_cause: float) -> list[dict]:
    """Queues with publishes in the window before the cause that no
    ring ever consumed — the frames the victim took down with it."""
    pub: dict = collections.defaultdict(int)
    con: dict = collections.defaultdict(int)
    last_pub: dict = {}
    for ev in timeline:
        if ev["t_aligned"] > t_cause:
            break
        q = ev.get("queue")
        if not q:
            continue
        if ev.get("kind") == "publish":
            if ev["t_aligned"] >= t_cause - IN_FLIGHT_WINDOW_S:
                pub[q] += 1
                last_pub[q] = ev
        elif ev.get("kind") == "consume":
            con[q] += 1
    out = []
    for q, n in sorted(pub.items()):
        missing = n - con.get(q, 0)
        if missing > 0:
            out.append({"queue": q, "frames": missing,
                        "last_publisher": last_pub[q].get("owner"),
                        "t_last": round(last_pub[q]["t_aligned"], 3)})
    return out


def stalled_barrier(timeline: list[dict],
                    cause: dict) -> dict | None:
    """The server barrier in progress at the cause: the last barrier
    span the server CLOSED before the death tells us which one it was
    stalled in after it (barriers close in a fixed round order)."""
    last = None
    for ev in timeline:
        if ev["t_aligned"] > cause["t_aligned"]:
            # a barrier that closed AFTER the cause within the same
            # round means the round survived; keep the last pre-cause
            # view regardless — the snapshot freezes at the cause
            break
        if ev.get("kind") == "span" and ev.get("owner_role") == "server" \
                and ev.get("name") in BARRIER_ORDER:
            last = ev
    if last is None:
        # death before any barrier closed: the first barrier is it
        return {"barrier": BARRIER_ORDER[0], "round": cause.get("round")}
    idx = BARRIER_ORDER.index(last["name"])
    if idx + 1 < len(BARRIER_ORDER):
        return {"barrier": BARRIER_ORDER[idx + 1],
                "round": last.get("round")}
    return {"barrier": BARRIER_ORDER[0],
            "round": (last.get("round") or 0) + 1}


# -- assembly ---------------------------------------------------------------

def assemble(root: str | pathlib.Path) -> dict:
    """The full postmortem document for one artifacts directory."""
    root = pathlib.Path(root)
    dumps = load_fleet(root)
    spans = load_spans(root)
    rounds = load_rounds(root)
    offsets = estimate_offsets(spans)
    timeline = build_timeline(dumps, offsets)
    cause = find_cause(timeline)
    doc: dict = {
        "root": str(root),
        "dumps": [{
            "participant": d.get("participant"),
            "role": d.get("role"),
            "reason": d.get("reason"),
            "pid": d.get("pid"),
            "t_dump": d.get("t_dump"),
            "events": len(d.get("events", [])),
            "dropped": d.get("dropped", 0),
            "torn": bool(d.get("torn")),
            "path": d.get("_path"),
        } for d in sorted(dumps,
                          key=lambda d: str(d.get("participant")))],
        "clock_offsets": {k: round(v, 6)
                          for k, v in sorted(offsets.items())},
        "events": len(timeline),
        "last_completed_round": (rounds[-1].get("round_idx")
                                 if rounds else None),
    }
    if cause is None:
        doc["verdict"] = {"abnormal": False,
                          "summary": "no abnormal termination"}
        return doc
    victim, role = _victim_of(cause)
    rnd = cause.get("round")
    if rnd is None and rounds:
        rnd = (rounds[-1].get("round_idx") or 0) + 1
    barrier = stalled_barrier(timeline, cause)
    tail = [e for e in timeline
            if e.get("kind") in ABNORMAL_KINDS][:8]
    doc["verdict"] = {
        "abnormal": True,
        "victim": victim,
        "role": role,
        "round": rnd,
        "cause": {k: v for k, v in cause.items()
                  if not k.startswith("_")},
        "reported_by": cause.get("owner"),
        "stalled_barrier": barrier,
        "in_flight": in_flight_frames(timeline, cause["t_aligned"]),
        "abnormal_events": [
            {"t": round(e["t_aligned"], 3), "kind": e.get("kind"),
             "owner": e.get("owner"),
             "participant": e.get("participant"),
             "sig": e.get("sig"), "round": e.get("round")}
            for e in tail],
        "summary": (f"{victim} ({role}) died"
                    + (f" in round {rnd}" if rnd is not None else "")
                    + f": first abnormal event {cause.get('kind')}"
                    f" reported by {cause.get('owner')}"),
    }
    return doc


def render(doc: dict) -> str:
    lines = [f"postmortem: {doc['root']}",
             f"  dumps: {len(doc['dumps'])}  "
             f"events: {doc['events']}  "
             f"last completed round: {doc['last_completed_round']}"]
    for d in doc["dumps"]:
        torn = "  [TORN]" if d["torn"] else ""
        lines.append(
            f"    {d['participant']} ({d['role']}) reason="
            f"{d['reason']} events={d['events']}"
            f" dropped={d['dropped']}{torn}")
    v = doc["verdict"]
    lines.append("")
    if not v["abnormal"]:
        lines.append("verdict: CLEAN — no abnormal termination")
        return "\n".join(lines)
    lines.append(f"verdict: {v['summary']}")
    c = v["cause"]
    lines.append(f"  cause: kind={c.get('kind')} t={c.get('t')} "
                 f"owner={v['reported_by']}")
    if v.get("stalled_barrier"):
        b = v["stalled_barrier"]
        lines.append(f"  stalled barrier: {b.get('barrier')} "
                     f"(round {b.get('round')})")
    for f in v.get("in_flight", []):
        lines.append(f"  in flight: {f['frames']} frame(s) on "
                     f"{f['queue']} (last publisher "
                     f"{f['last_publisher']})")
    if len(v.get("abnormal_events", [])) > 1:
        lines.append("  cascade:")
        for e in v["abnormal_events"]:
            who = e.get("participant") or e.get("sig") or ""
            lines.append(f"    t={e['t']} {e['kind']} "
                         f"[{e['owner']}] {who}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Assemble blackbox dumps into a causal "
                    "cross-process postmortem report.")
    ap.add_argument("root", help="artifacts directory holding "
                                 "blackbox-*.json (searched "
                                 "recursively)")
    ap.add_argument("-o", "--out", default=None,
                    help="also write the postmortem JSON here")
    ap.add_argument("--format", choices=("human", "json"),
                    default="human")
    args = ap.parse_args(argv)
    doc = assemble(args.root)
    if args.out:
        pathlib.Path(args.out).write_text(
            json.dumps(doc, indent=2, default=str))
    if args.format == "json":
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(render(doc))
    # exit 0 either way: reporting an abnormal death is this tool
    # WORKING, not failing — rigs assert on the verdict contents
    return 0


if __name__ == "__main__":
    sys.exit(main())
