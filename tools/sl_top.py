#!/usr/bin/env python
"""``sl_top`` — live terminal view of the training fleet.

Polls the server's telemetry endpoint (``observability.http-port``,
``runtime/telemetry.py TelemetryExporter``) for the ``/fleet`` JSON
snapshot and renders a per-client health table: state, current round,
EWMA samples/s, straggler score (rate / fleet median), frame-RTT p95,
cumulative wire MB and heartbeat age.  In watch mode a transient
scrape failure keeps the last table on screen and retries (a top-style
monitor must not die on a blip); ``--journal`` reads the server's
``kind=fleet`` records from a run's ``metrics.jsonl`` instead — the
post-hoc view of the same data.

    python tools/sl_top.py --url http://127.0.0.1:9090        # live
    python tools/sl_top.py --url http://127.0.0.1:9090 --once # one shot
    python tools/sl_top.py --journal artifacts/runs/<run_id>  # tail

Stdlib only (urllib + json): runs anywhere the repo does.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time
import urllib.request

_STATE_COLOR = {"healthy": "\033[92m", "degraded": "\033[93m",
                "straggler": "\033[95m", "lost": "\033[91m",
                "down": "\033[91m"}
_RESET = "\033[0m"

_COLUMNS = ("PARTICIPANT", "ROLE", "STATE", "CLUSTER", "SCHED",
            "ROUND", "VLAG", "SAMPLES", "RATE/s", "QDEPTH", "SCORE",
            "MFU", "STEP p95 ms", "RTT p95 ms", "WIRE MB", "BLACKBOX",
            "AGE s")


def _blackbox_cell(c: dict) -> str:
    """Flight-recorder health: ``<ring depth>/<last-dump age>`` from
    the ``blackbox_*`` gauges heartbeats carry (``runtime/blackbox``).
    "-" for participants without a recorder; age "never" until the
    first dump."""
    depth = c.get("blackbox_ring_depth")
    if depth is None:
        return "-"
    age = c.get("blackbox_last_dump_age_s")
    if age is None or age < 0:
        return f"{int(depth)}/never"
    return f"{int(depth)}/{age:.0f}s"

#: telemetry snapshot `kind` -> table role label; aggregator nodes
#: (aggregation.remote) rate-columns read "-": their samples/s is
#: structurally 0, the AGG gauges carry their load instead.  Stage
#: hosts (pipeline.remote) DO rate: their samples/s is the sum of
#: their slots' hot loops, their CLUSTER column carries the stage id
#: and QDEPTH their summed ingest backlog.
_ROLE = {"client": "client", "agg_node": "agg", "stage_host": "stage"}


def _broker_rows(brokers: list) -> list[tuple]:
    """ROLE=broker table rows from the /fleet ``brokers`` block (one
    per shard; ``broker.shards``).  Training columns are structurally
    empty — a shard's load lives in the summary line and the WIRE/AGE
    columns (bytes moved, uptime)."""
    rows = []
    for s in brokers:
        dead = "error" in s
        name = s.get("shard") or f"shard_{s.get('shard_index', '?')}" \
            f"@{s.get('port', '?')}"
        wire_mb = (s.get("bytes_in", 0) + s.get("bytes_out", 0)) / 1e6
        rows.append((
            name, "broker", "down" if dead else "up",
            "-", "-", "-", "-",
            "-" if dead else _fmt(s.get("depth")),       # queued msgs
            "-", "-", "-", "-", "-",
            f"{wire_mb:.2f}", "-",
            "-" if dead else _fmt(s.get("uptime_s"))))
    return rows


def fetch_fleet(url: str, timeout: float = 3.0) -> dict:
    with urllib.request.urlopen(f"{url.rstrip('/')}/fleet",
                                timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def journal_files(path: pathlib.Path) -> list[pathlib.Path]:
    """metrics.jsonl plus its size-rotated siblings
    (``observability.metrics-max-mb``), oldest first — so scanning
    them in order reads exactly like one unrotated file."""
    if path.is_dir():
        path = path / "metrics.jsonl"
    rotated = []
    for p in path.parent.glob(path.name + ".*"):
        suffix = p.name.rsplit(".", 1)[-1]
        if suffix.isdigit():
            rotated.append((int(suffix), p))
    out = [p for _, p in sorted(rotated, reverse=True)]
    if path.exists():
        out.append(path)
    return out


def fleet_from_journal(path: pathlib.Path) -> dict | None:
    """Latest ``kind=fleet`` record from a metrics.jsonl (or a run
    directory holding one), rotated files included."""
    latest = None
    for p in journal_files(pathlib.Path(path)):
        for line in p.read_text().splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "fleet" and isinstance(
                    rec.get("fleet"), dict):
                latest = rec["fleet"]
    return latest


def _fmt(v, nd=1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


#: past this many per-client rows the table collapses to the worst-K
#: view (a 10k-row terminal table is unreadable and slow) — override
#: with --top/--all
DEFAULT_TOP = 48

_STATE_SEV = {"healthy": 0, "degraded": 1, "straggler": 2, "lost": 3}


def _severity_key(item):
    cid, c = item
    score = c.get("straggler_score")
    return (-_STATE_SEV.get(c.get("state", "healthy"), 0),
            score if score is not None else float("inf"), cid)


def render_fleet(fleet: dict, color: bool = True,
                 source: str = "", top: int | None = None) -> str:
    """The fleet table as one string (tested, and reused by --once).

    Above ``top`` clients (default :data:`DEFAULT_TOP`; None = all)
    only the WORST rows render — ranked by health-state severity then
    straggler score — under a summary header; with the digest roll-up
    active the header also carries the fleet-wide quantiles and the
    per-node digest summary."""
    counts = fleet.get("counts", {})
    clients = fleet.get("clients", {})
    head = ("fleet @ " + time.strftime(
        "%H:%M:%S", time.localtime(fleet.get("t", time.time())))
        + (f"  [{source}]" if source else "")
        + "  |  " + " ".join(f"{s}={n}" for s, n in counts.items()))
    summary: list[str] = []
    dig = fleet.get("digest") or {}
    if dig:
        q = dig.get("quantiles") or {}
        summary.append(
            f"digest: {dig.get('clients', 0)} clients across "
            f"{len(dig.get('nodes') or {})} node(s)"
            + (f"  rate p50={q.get('rate_p50')}/s "
               f"p95={q.get('rate_p95')}/s" if q else "")
            + (f"  watchlist={len(fleet.get('watchlist') or [])}"
               if fleet.get("watchlist") is not None else ""))
    brokers = fleet.get("brokers") or []
    if brokers:
        live = [s for s in brokers if "error" not in s]
        summary.append(
            f"brokers: {len(live)}/{len(brokers)} shard(s) up, "
            f"{sum(s.get('conns', 0) for s in live)} conns, "
            f"{sum(s.get('parked_gets', 0) for s in live)} parked "
            f"gets, depth hwm "
            f"{max((s.get('depth_hwm', 0) for s in live), default=0)}")
    shown = sorted(clients.items())
    if top is not None and len(shown) > top:
        shown = sorted(shown, key=_severity_key)[:top]
        summary.append(
            f"showing worst {len(shown)} of {len(clients)} tracked "
            "rows (--all for every row; severity-ranked)")
    rows = [_COLUMNS]
    rows += _broker_rows(brokers)
    for cid, c in shown:
        wire_mb = (c.get("wire_bytes_out") or 0) / 1e6
        agg = c.get("kind") == "agg_node"
        stage_host = c.get("kind") == "stage_host"
        # stage-host rows (pipeline.remote) show the stage their slots
        # run where clients show their scheduler cluster
        cluster_cell = (f"s{c['stage']}"
                        if stage_host and c.get("stage") is not None
                        else _fmt(c.get("cluster")))
        rows.append((
            cid, _ROLE.get(c.get("kind", "client"), c.get("kind")),
            c.get("state", "?"),
            # closed-loop scheduler (scheduler.enabled): assigned
            # online cluster + last scheduler action ("demote@r3");
            # "-" with the scheduler off or for unclustered roles
            cluster_cell, _fmt(c.get("sched")),
            _fmt(c.get("round")),
            # async version lag (bounded-staleness mode); "-" outside it
            _fmt(c.get("version_lag")),
            # aggregator rows: training columns are structurally empty
            "-" if agg else _fmt(c.get("samples")),
            "-" if agg else _fmt(c.get("samples_per_s")),
            # later-stage ingest backlog (pipeline plane); "-" for
            # pre-plane participants whose beats never carried it
            _fmt(c.get("queue_depth")),
            _fmt(c.get("straggler_score"), 2),
            # perf-plane gauges (runtime/perf.py); "-" for clients
            # predating the plane
            _fmt(c.get("mfu"), 4), _fmt(c.get("step_p95_ms"), 2),
            _fmt(c.get("rtt_p95_ms"), 2),
            f"{wire_mb:.2f}", _blackbox_cell(c), _fmt(c.get("age_s")),
        ))
    widths = [max(len(str(r[i])) for r in rows)
              for i in range(len(_COLUMNS))]
    lines = [head, *summary,
             "-" * (sum(widths) + 2 * (len(widths) - 1))]
    for ri, row in enumerate(rows):
        cells = [f"{str(v):<{w}}" for v, w in zip(row, widths)]
        line = "  ".join(cells)
        if color and ri > 0:
            c = _STATE_COLOR.get(row[2])
            if c:
                line = f"{c}{line}{_RESET}"
        lines.append(line)
    tail = fleet.get("transitions", [])[-5:]
    if tail:
        lines.append("")
        lines.append("recent transitions:")
        for t in tail:
            lines.append(f"  {t.get('client')}: {t.get('from')} -> "
                         f"{t.get('to')} ({t.get('why')})")
    sched = fleet.get("scheduler") or {}
    dec = [d for d in sched.get("decisions", [])
           if d.get("action") != "decide"][-5:]
    if dec:
        lines.append("")
        lines.append("recent scheduler decisions:")
        for d in dec:
            who = d.get("client") or f"cluster {d.get('cluster')}"
            lines.append(f"  r{d.get('round')}: {d.get('action')} "
                         f"{who} ({d.get('why')})")
    if sched.get("last_replan"):
        rp = sched["last_replan"]
        lines.append(f"last re-plan: r{rp.get('round')} cluster "
                     f"{rp.get('cluster')} cuts {rp.get('cuts_from')}"
                     f" -> {rp.get('cuts_to')}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Live fleet telemetry view (polls /fleet, or "
                    "tails a run's metrics.jsonl).")
    ap.add_argument("--url", default="http://127.0.0.1:9090",
                    help="server telemetry endpoint "
                         "(observability.http-port)")
    ap.add_argument("--journal", default=None, metavar="DIR",
                    help="instead of polling: read the latest "
                         "kind=fleet record from DIR/metrics.jsonl")
    ap.add_argument("--broker", default=None, metavar="HOST:PORT[:N]",
                    help="instead of a server: poll N broker shards' "
                         "stats control queues directly (default "
                         "N=1) and render the ROLE=broker rows")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="render one snapshot and exit")
    ap.add_argument("--no-color", action="store_true")
    ap.add_argument("--top", type=int, default=DEFAULT_TOP,
                    help="past this many clients, show only the "
                         "worst rows (severity-ranked); see --all")
    ap.add_argument("--all", action="store_true",
                    help="always render every per-client row")
    args = ap.parse_args(argv)
    top = None if args.all else args.top

    def snap() -> tuple[dict | None, str, str]:
        if args.broker:
            # not stdlib-only like the default path: the shard stats
            # ride the repo's own broker wire protocol
            try:
                from split_learning_tpu.runtime.bus import (
                    collect_broker_stats,
                )
            except ImportError:
                sys.path.insert(0, str(pathlib.Path(
                    __file__).resolve().parent.parent))
                from split_learning_tpu.runtime.bus import (
                    collect_broker_stats,
                )
            host, _, rest = args.broker.partition(":")
            port, _, n = rest.partition(":")
            try:
                brokers = collect_broker_stats(host, int(port),
                                               int(n or 1))
            except Exception as e:  # noqa: BLE001 — plane down
                return None, args.broker, str(e)
            return ({"clients": {}, "counts": {}, "t": time.time(),
                     "brokers": brokers}, args.broker, "")
        if args.journal:
            return (fleet_from_journal(pathlib.Path(args.journal)),
                    args.journal, "no kind=fleet record found")
        try:
            return fetch_fleet(args.url), args.url, ""
        except Exception as e:  # noqa: BLE001 — URLError, truncated
            # body, bad JSON mid-teardown: all just "not reachable now"
            return None, args.url, str(e)

    last = ""
    while True:
        fleet, source, why = snap()
        if fleet is None and args.once:
            print(f"sl_top: cannot read {source}: {why}",
                  file=sys.stderr)
            return 1
        if fleet is not None:
            last = render_fleet(fleet, color=not args.no_color,
                                source=source, top=top)
            if args.once:
                print(last)
                return 0
            out = last
        else:
            # transient blip: keep the last table, keep polling
            out = (last + "\n\n" if last else "") \
                + f"[{source} unreachable: {why} — retrying]"
        sys.stdout.write("\033[2J\033[H" + out + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
