"""Label-count-driven dataset subsetting + batching.

Parity surface: the reference's ``data_loader(data_name, batch_size,
distribution, train)`` (``/root/reference/src/dataset/dataloader.py:124-133``)
where ``distribution`` is a per-label sample-count vector and each loader
samples exactly that many examples per class (``:61-92``).

TPU-first differences:

* batches are numpy arrays with **static shapes** (``drop_last`` semantics:
  a trailing partial batch would retrigger XLA compilation, so it is folded
  by wrapping around the shuffled epoch instead of being emitted ragged);
* augmentation (random crop + horizontal flip for CIFAR) is pure numpy on
  host, overlapping with device compute;
* everything is seeded and deterministic.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Callable, Iterator

import numpy as np


def subset_seed(base_seed: int, client_key: str, round_idx: int = 0,
                refresh: bool = False) -> int:
    """Loader seed for one client's label-count subset draw.

    crc32, not ``hash()`` (salted per process): two clients with
    identical label counts must still draw DISTINCT subsets, and the
    same deployment must draw the same ones on every run.  With
    ``refresh`` (the reference's ``data-distribution.refresh`` —
    clients rebuild their loader every round, ``src/RpcClient.py:108``)
    the seed also varies per round, re-sampling the subset."""
    s = (zlib.crc32(client_key.encode()) ^ base_seed) % (2 ** 31)
    if refresh:
        s = (s ^ (0x9E3779B1 * (round_idx + 1))) % (2 ** 31)
    return s


def label_count_subset(labels: np.ndarray, counts: np.ndarray,
                       rng: np.random.Generator) -> np.ndarray:
    """Indices selecting exactly ``counts[c]`` examples of each class c.

    If a class has fewer examples than requested, sampling wraps with
    replacement (the reference errors out instead; wrapping keeps synthetic
    smoke datasets usable at any requested scale).
    """
    idx: list[np.ndarray] = []
    for c, n in enumerate(np.asarray(counts, dtype=int)):
        if n <= 0:
            continue
        pool = np.nonzero(labels == c)[0]
        if len(pool) == 0:
            continue
        replace = len(pool) < n
        idx.append(rng.choice(pool, size=n, replace=replace))
    if not idx:
        return np.empty((0,), dtype=int)
    out = np.concatenate(idx)
    rng.shuffle(out)
    return out


@dataclasses.dataclass
class ArrayDataset:
    """In-memory dataset: ``inputs`` is one array or a dict of arrays
    (e.g. BERT's input_ids/attention_mask), ``labels`` is int."""
    inputs: np.ndarray | dict
    labels: np.ndarray

    def __len__(self) -> int:
        return len(self.labels)

    def take(self, idx: np.ndarray) -> "ArrayDataset":
        if isinstance(self.inputs, dict):
            ins = {k: v[idx] for k, v in self.inputs.items()}
        else:
            ins = self.inputs[idx]
        return ArrayDataset(ins, self.labels[idx])


class DataLoader:
    """Seeded shuffling batcher with static batch shapes.

    ``augment`` maps a stacked input batch -> augmented batch (numpy).
    Iterating yields ``(inputs, labels)``; ``len()`` is batches/epoch.
    """

    def __init__(self, dataset: ArrayDataset, batch_size: int,
                 shuffle: bool = True,
                 augment: Callable[[np.ndarray, np.random.Generator],
                                   np.ndarray] | None = None,
                 seed: int = 0):
        if len(dataset) == 0:
            raise ValueError("empty dataset")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.augment = augment
        self._rng = np.random.default_rng(seed)
        self.num_batches = max(1, len(dataset) // batch_size)

    @property
    def samples_per_epoch(self) -> int:
        return self.num_batches * self.batch_size

    def __len__(self) -> int:
        return self.num_batches

    def __iter__(self) -> Iterator[tuple]:
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            self._rng.shuffle(order)
        need = self.num_batches * self.batch_size
        if n < need:
            # wrap (with repetition for tiny datasets) to fill the static
            # batch shape
            reps = -(-need // n)
            order = np.tile(order, reps)[:need]
        for b in range(self.num_batches):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            batch = self.dataset.take(idx)
            ins = batch.inputs
            if self.augment is not None:
                ins = self.augment(ins, self._rng)
            yield ins, batch.labels


def cifar_augment(x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """Random crop (pad 4) + horizontal flip, NHWC — the reference's
    torchvision transform pipeline (``src/dataset/dataloader.py:63-70``)
    in numpy."""
    b, h, w, _ = x.shape
    padded = np.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    out = np.empty_like(x)
    ys = rng.integers(0, 9, size=b)
    xs = rng.integers(0, 9, size=b)
    flip = rng.random(b) < 0.5
    for i in range(b):
        crop = padded[i, ys[i]:ys[i] + h, xs[i]:xs[i] + w]
        out[i] = crop[:, ::-1] if flip[i] else crop
    return out
