"""Greedy longest-match WordPiece tokenization (BERT-style), offline.

The reference tokenizes AG-News with a pretrained ``BertTokenizer``
(``/root/reference/src/dataset/AGNEWS.py:13-30``, 28996-entry cased
vocab).  This module reproduces that pipeline without network egress:
drop the tokenizer's ``vocab.txt`` under ``data_dir()`` (see
:func:`find_vocab` for the searched locations) and AG-News token ids
match the pretrained tokenizer; with no vocab on disk the caller falls
back to hash tokenization (``datasets._hash_tokenize``).

Algorithm (classic BERT):

1. basic tokenization — whitespace split, punctuation split off as
   single-char tokens, CJK chars isolated, control chars dropped
   (cased: no lower-casing, no accent stripping);
2. per word, greedy longest-match against the vocab with ``##``
   continuation prefixes; words with no match become ``[UNK]``;
3. ``[CLS] tokens [SEP]``, truncated/padded to ``seq_len`` with
   ``[PAD]`` (id 0); attention_mask marks real tokens.
"""

from __future__ import annotations

import pathlib
import unicodedata

import numpy as np

_MAX_WORD_CHARS = 100  # HF parity: longer words become [UNK] outright


def load_vocab(path: str | pathlib.Path) -> dict[str, int]:
    """vocab.txt (one token per line, line number = id) -> token->id."""
    vocab: dict[str, int] = {}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            tok = line.rstrip("\n")
            if tok:
                vocab[tok] = i
    return vocab


def find_vocab(data_root: pathlib.Path) -> pathlib.Path | None:
    """First vocab.txt found under the conventional locations."""
    for rel in ("vocab.txt", "bert/vocab.txt", "tokenizer/vocab.txt",
                "bert-base-cased/vocab.txt"):
        p = data_root / rel
        if p.exists():
            return p
    return None


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII ranges HF treats as punctuation even when unicodedata doesn't
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(ch: str) -> bool:
    cp = ord(ch)
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0xF900 <= cp <= 0xFAFF)


def basic_tokenize(text: str, lower_case: bool = False) -> list[str]:
    """Whitespace + punctuation + CJK splitting (HF BasicTokenizer)."""
    if lower_case:
        text = text.lower()
    out: list[str] = []
    word: list[str] = []

    def flush():
        if word:
            out.append("".join(word))
            word.clear()

    for ch in text:
        cat = unicodedata.category(ch)
        if ch in ("\t", "\n", "\r") or ch == " " or cat == "Zs":
            flush()
        elif cat.startswith("C"):  # control chars dropped
            continue
        elif _is_punctuation(ch) or _is_cjk(ch):
            flush()
            out.append(ch)
        else:
            word.append(ch)
    flush()
    return out


class WordPieceTokenizer:
    """Greedy longest-match WordPiece over a loaded vocab."""

    def __init__(self, vocab: dict[str, int], lower_case: bool = False,
                 unk_token: str = "[UNK]"):
        self.vocab = vocab
        self.lower_case = lower_case
        self.unk_id = vocab[unk_token]
        self.cls_id = vocab["[CLS]"]
        self.sep_id = vocab["[SEP]"]
        self.pad_id = vocab.get("[PAD]", 0)

    @classmethod
    def from_file(cls, path: str | pathlib.Path, **kw):
        return cls(load_vocab(path), **kw)

    def wordpiece(self, word: str) -> list[int]:
        if len(word) > _MAX_WORD_CHARS:
            return [self.unk_id]
        ids: list[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = self.vocab[sub]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]  # whole word -> [UNK] (HF parity)
            ids.append(cur)
            start = end
        return ids

    def tokenize(self, text: str) -> list[int]:
        ids: list[int] = []
        for word in basic_tokenize(text, self.lower_case):
            ids.extend(self.wordpiece(word))
        return ids

    def encode(self, text: str, seq_len: int) -> np.ndarray:
        """[CLS] ids [SEP] padded/truncated to seq_len (HF
        ``max_length``/``truncation=True``/``padding='max_length'``)."""
        ids = self.tokenize(text)[:seq_len - 2]
        row = [self.cls_id] + ids + [self.sep_id]
        out = np.full((seq_len,), self.pad_id, np.int32)
        out[:len(row)] = row
        return out

    def encode_batch(self, texts: list[str], seq_len: int) -> np.ndarray:
        return np.stack([self.encode(t, seq_len) for t in texts])
