"""``python -m split_learning_tpu.data --fetch cifar10`` entry point."""
from split_learning_tpu.data.fetch import main

raise SystemExit(main())
