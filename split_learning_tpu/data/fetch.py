"""Operator-facing dataset fetcher (VERDICT r4 missing #4).

The reference's Vanilla_SL clients download and subset their datasets
themselves at startup (``/root/reference/other/Vanilla_SL/src/
RpcClient.py:64-88``, torchvision/torchaudio ``download=True``); this
module is that operational surface for machines WITH network access:

    python -m split_learning_tpu.data --fetch cifar10
    python -m split_learning_tpu.data --fetch all --dest /data

Each fetch downloads the public archive, extracts it into the layout
:mod:`split_learning_tpu.data.datasets` already reads (``SLT_DATA_DIR``,
default ``./data``), and verifies the loader's probe file exists.  On a
zero-egress host the command fails with a clear message and the loaders
keep their synthetic fallback — exactly the reference's behavior class
when its downloads fail, minus the stack trace.
"""

from __future__ import annotations

import gzip
import hashlib
import pathlib
import shutil
import tarfile
import tempfile
import urllib.request

from split_learning_tpu.data.datasets import data_dir

#: upstream archive sha256 pins (ADVICE round 5): verified against the
#: published torchvision/TFDS checksums for these fixed-URL archives.
#: A pin of None skips verification (the agnews CSVs live at a mutable
#: git raw URL with no stable published digest — logged loudly).
_MNIST_SHA256 = {
    "train-images-idx3-ubyte":
        "440fcabf73cc546fa21475e81ea370265605f56be210a4024d2ca8f203523609",
    "train-labels-idx1-ubyte":
        "3552534a0a558bbed6aed32b30c495cca23d567ec52cac8be1a0730e8010255c",
    "t10k-images-idx3-ubyte":
        "8d422c7b0a1c1c79245a5bcf07fe86e33eeafee792b84584aec276f5a2dbc4e6",
    "t10k-labels-idx1-ubyte":
        "f7ae60f92e00ec6debd23a6088c31dbd2371eca3ffa0defaefb259924204aec6",
}

#: name -> (list of (url, archive kind, member-handling, sha256), probe
#: path).  kinds: "targz" (extract under dest), "gz-raw" (gunzip single
#: file to the given relative path), "raw" (save as-is to the relative
#: path).  The sha256 is of the DOWNLOADED bytes (the archive, not its
#: extraction) and is checked before anything is unpacked.
_SPECS: dict = {
    "cifar10": {
        "files": [("https://www.cs.toronto.edu/~kriz/"
                   "cifar-10-python.tar.gz", "targz", None,
                   "6d958be074577803d12ecdefd02955f3"
                   "9262c83c16fe9348329d7fe0b5c001ce")],
        "probe": "cifar-10-batches-py/data_batch_1",
    },
    "cifar100": {
        "files": [("https://www.cs.toronto.edu/~kriz/"
                   "cifar-100-python.tar.gz", "targz", None,
                   "85cd44d02ba6437773c5bbd22e183051"
                   "d648de2e7d6b014e1ef29b855ba677a7")],
        "probe": "cifar-100-python/train",
    },
    "mnist": {
        "files": [
            (f"https://ossci-datasets.s3.amazonaws.com/mnist/{stem}.gz",
             "gz-raw", f"MNIST/raw/{stem}", _MNIST_SHA256[stem])
            for stem in ("train-images-idx3-ubyte",
                         "train-labels-idx1-ubyte",
                         "t10k-images-idx3-ubyte",
                         "t10k-labels-idx1-ubyte")
        ],
        "probe": "MNIST/raw/train-images-idx3-ubyte",
    },
    "agnews": {
        "files": [
            ("https://raw.githubusercontent.com/mhjabreel/CharCnn_Keras/"
             f"master/data/ag_news_csv/{name}.csv", "raw",
             f"ag_news/{name}.csv", None)
            for name in ("train", "test")
        ],
        "probe": "ag_news/train.csv",
    },
    "speechcommands": {
        "files": [("https://download.tensorflow.org/data/"
                   "speech_commands_v0.02.tar.gz", "targz",
                   "SpeechCommands/speech_commands_v0.02",
                   "af14739ee7dc311471de98f5f9d2c919"
                   "1b18aedfe957f4a6ff791c709868ff58")],
        "probe": "SpeechCommands/speech_commands_v0.02/"
                 "validation_list.txt",
    },
}


def _verify_sha256(path: pathlib.Path, expected: str | None, url: str,
                   log=print) -> None:
    """Check a downloaded file against its pin BEFORE it is unpacked."""
    if expected is None:
        log(f"[fetch] WARNING: no pinned sha256 for {url}; "
            "skipping integrity verification")
        return
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    got = h.hexdigest()
    if got != expected:
        raise RuntimeError(
            f"sha256 mismatch for {url}: expected {expected}, got "
            f"{got}. The upstream file changed or the download was "
            "tampered with; refusing to install it.")


def _safe_members(tar: tarfile.TarFile) -> list:
    """Pre-``filter=`` traversal guard: reject members (and link
    targets) with absolute paths or ``..`` components so a tampered
    archive cannot write outside the extraction root on interpreters
    without ``extractall(filter='data')``."""
    members = tar.getmembers()
    for m in members:
        paths = [("member", m.name)]
        if m.issym() or m.islnk():
            paths.append(("link target", m.linkname))
        for label, name in paths:
            p = pathlib.PurePosixPath(name)
            if p.is_absolute() or ".." in p.parts:
                raise RuntimeError(
                    f"refusing to extract: {label} {name!r} escapes "
                    "the extraction root (path traversal)")
    return members


def fetchable() -> list[str]:
    return sorted(_SPECS)


def fetch(name: str, dest: str | pathlib.Path | None = None,
          urlopen=urllib.request.urlopen, log=print) -> pathlib.Path:
    """Download + install one dataset; returns the probe path.

    ``urlopen`` is injectable so the install/extract logic is testable
    on a zero-egress host (tests serve local fixture archives).
    """
    spec = _SPECS.get(name.lower())
    if spec is None:
        raise KeyError(
            f"unknown dataset {name!r}; fetchable: {fetchable()}")
    root = pathlib.Path(dest) if dest is not None else data_dir()
    root.mkdir(parents=True, exist_ok=True)
    # ATOMIC install: everything downloads and extracts into a staging
    # dir first, and moves into the live layout only once every file of
    # the dataset succeeded — a mid-fetch network drop must not leave
    # e.g. real MNIST train files next to a synthetic-fallback test
    # split (silently validating against a different distribution).
    staging = pathlib.Path(tempfile.mkdtemp(prefix=f"slt_fetch_{name}_",
                                            dir=root))
    try:
        for url, kind, member, sha256 in spec["files"]:
            log(f"[fetch] {url}")
            try:
                resp = urlopen(url, timeout=60)
            except Exception as e:
                raise RuntimeError(
                    f"download failed for {url} "
                    f"({type(e).__name__}: {e}). No network egress? "
                    f"Stage the files under {root} manually, or keep "
                    "the synthetic fallback."
                ) from e
            with tempfile.NamedTemporaryFile(delete=False) as tmp:
                shutil.copyfileobj(resp, tmp)
                tmp_path = pathlib.Path(tmp.name)
            try:
                _verify_sha256(tmp_path, sha256, url, log=log)
                if kind == "targz":
                    with tarfile.open(tmp_path, "r:gz") as tar:
                        target = staging
                        if member is not None:
                            # archives whose members are top-level
                            # (e.g. speech_commands) extract into a
                            # named subdir
                            target = staging / member
                            target.mkdir(parents=True, exist_ok=True)
                        try:
                            tar.extractall(target, filter="data")
                        except TypeError:
                            # filter= needs >=3.10.12/3.11.4; reject
                            # traversal-shaped members ourselves on
                            # stock older interpreters
                            tar.extractall(
                                target, members=_safe_members(tar))
                elif kind == "gz-raw":
                    out = staging / member
                    out.parent.mkdir(parents=True, exist_ok=True)
                    with gzip.open(tmp_path, "rb") as src, \
                            open(out, "wb") as dst:
                        shutil.copyfileobj(src, dst)
                else:   # raw
                    out = staging / member
                    out.parent.mkdir(parents=True, exist_ok=True)
                    shutil.move(str(tmp_path), out)
                    continue
            finally:
                tmp_path.unlink(missing_ok=True)
        if not (staging / spec["probe"]).exists():
            raise RuntimeError(
                f"fetch of {name} completed but the loader probe file "
                f"{spec['probe']} is missing — archive layout changed "
                "upstream?")
        for entry in staging.iterdir():
            final = root / entry.name
            if final.exists():
                shutil.rmtree(final, ignore_errors=True)
            shutil.move(str(entry), final)
    finally:
        shutil.rmtree(staging, ignore_errors=True)
    probe = root / spec["probe"]
    log(f"[fetch] {name} ready under {root} "
        f"(set SLT_DATA_DIR={root} if not ./data)")
    return probe


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="Download real datasets into the layout the "
                    "framework's loaders read (reference parity: "
                    "Vanilla_SL clients self-download).")
    ap.add_argument("--fetch", required=True,
                    help=f"dataset name or 'all' ({fetchable()})")
    ap.add_argument("--dest", default=None,
                    help="target directory (default: $SLT_DATA_DIR or "
                         "./data)")
    args = ap.parse_args(argv)
    names = fetchable() if args.fetch == "all" else [args.fetch]
    for n in names:
        fetch(n, dest=args.dest)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
