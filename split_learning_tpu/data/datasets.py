"""Dataset providers with on-disk loading + deterministic synthetic fallback.

Parity surface: the reference's per-dataset loaders
(``/root/reference/src/dataset/dataloader.py``): CIFAR-10 via torchvision
(+augment), AG-News via CSV + BertTokenizer to fixed length 128, and
SpeechCommands via a manual MFCC pipeline with ``validation_list.txt`` /
``testing_list.txt`` splits.

This environment has zero egress, so each provider first looks for the
real data under ``data_dir`` (env ``SLT_DATA_DIR``, default ``./data``) in
its standard on-disk format and otherwise synthesizes a deterministic,
class-separable dataset with identical shapes/dtypes — tests, the protocol
integration suite, and benches run anywhere; real-data runs only need the
files dropped in place.
"""

from __future__ import annotations

import csv
import os
import pathlib
import pickle
from typing import Callable

import numpy as np

from split_learning_tpu.data.loader import (
    ArrayDataset, DataLoader, cifar_augment, label_count_subset,
)

_CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
_CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
_CIFAR100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
_CIFAR100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)

_PROVIDERS: dict[str, Callable] = {}


def data_dir() -> pathlib.Path:
    return pathlib.Path(os.environ.get("SLT_DATA_DIR", "data"))


def register_dataset(name: str):
    def deco(fn):
        _PROVIDERS[name] = fn
        return fn
    return deco


def dataset_registry() -> dict[str, Callable]:
    return dict(_PROVIDERS)


def get_dataset(name: str, train: bool = True,
                synthetic_size: int | None = None,
                **dataset_kwargs) -> ArrayDataset:
    """``dataset_kwargs`` forward to the provider (e.g. ``vocab`` for
    token datasets, so a model with an overridden ``vocab_size`` draws
    in-range ids — out-of-range ids NaN-fill in ``nn.Embed``)."""
    if name not in _PROVIDERS:
        raise KeyError(f"unknown dataset {name!r}; known: "
                       f"{sorted(_PROVIDERS)}")
    return _PROVIDERS[name](train=train, synthetic_size=synthetic_size,
                            **dataset_kwargs)


# --------------------------------------------------------------------------
# synthetic generators: class-separable so accuracy is a meaningful signal
# --------------------------------------------------------------------------

def _synthetic_images(n: int, shape: tuple, n_classes: int,
                      seed: int, train: bool = True) -> ArrayDataset:
    """Gaussian blobs: each class has a fixed random template + noise, so
    even small models can learn — validation accuracy moves off chance.

    The class templates depend ONLY on ``seed`` — train and val draw
    different samples/noise around the SAME templates.  (A previous
    revision re-drew the templates per split, which made the val set
    statistically unrelated to training and pinned val accuracy at
    chance forever — the bug VERDICT r2 'what's missing #1' smoked out.)

    Templates are SPATIALLY SMOOTH (a coarse 8x-block grid, like the
    low-frequency content of natural images), not iid pixel noise: the
    CIFAR train loader applies random-crop/flip augmentation
    (``cifar_augment``), and a few-pixel shift of an iid-noise template
    is nearly orthogonal to the original — training would see an
    (effectively) different task than validation and accuracy would pin
    at chance regardless of model or optimizer (round-5 flagship
    post-mortem).  Block templates keep ~75%+ correlation under the
    +-4 px crops, the property real images have that makes
    augmentation help rather than destroy."""
    rng_templates = np.random.default_rng(seed)
    if len(shape) < 2:
        # the block-kron construction assumes >= 2 leading SPATIAL dims
        # (its whole point is surviving 2-D crop/flip augmentation —
        # see the correlation rationale above).  1-D shapes (e.g. raw
        # audio) have no such augmentation here: fall back to iid
        # templates instead of emitting a silently mis-shaped tensor.
        templates = rng_templates.normal(0, 1,
                                         size=(n_classes,) + tuple(shape))
    else:
        block = 8
        coarse_sp = tuple(-(-s // block) for s in shape[:2])
        coarse = rng_templates.normal(
            0, 1, size=(n_classes,) + coarse_sp + shape[2:])
        ones = np.ones((1,) + (block, block) + (1,) * len(shape[2:]))
        templates = np.kron(coarse, ones)[
            (slice(None),) + tuple(slice(0, s) for s in shape[:2])]
    rng = np.random.default_rng(seed * 7919 + (1 if train else 2))
    labels = rng.integers(0, n_classes, size=n)
    x = (templates[labels] * 0.5
         + rng.normal(0, 1, size=(n,) + shape) * 0.5)
    return ArrayDataset(x.astype(np.float32), labels.astype(np.int32))


def _synthetic_tokens(n: int, seq_len: int, vocab: int, n_classes: int,
                      seed: int) -> ArrayDataset:
    """Each class owns a band of "topic" tokens mixed with common ones."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, size=n)
    band = vocab // (n_classes + 1)
    common = rng.integers(1, band, size=(n, seq_len))
    topic = (band * (labels[:, None] + 1)
             + rng.integers(0, band, size=(n, seq_len)))
    use_topic = rng.random((n, seq_len)) < 0.3
    ids = np.where(use_topic, topic, common).astype(np.int32)
    # [CLS]-like position: id 101 (the BERT [CLS] id), NOT 0 — id 0 is
    # [PAD] and would be masked out of attention (models/bert.py)
    ids[:, 0] = min(101, vocab - 1)
    return ArrayDataset(ids, labels.astype(np.int32))


# --------------------------------------------------------------------------
# CIFAR
# --------------------------------------------------------------------------

def _load_cifar_batches(root: pathlib.Path, files: list[str],
                        label_key: bytes) -> tuple | None:
    xs, ys = [], []
    for fname in files:
        p = root / fname
        if not p.exists():
            return None
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(np.asarray(d[b"data"], np.uint8))
        ys.append(np.asarray(d[label_key], np.int32))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return x, np.concatenate(ys)


def _cifar(train: bool, synthetic_size, n_classes: int):
    if n_classes == 10:
        root = data_dir() / "cifar-10-batches-py"
        files = ([f"data_batch_{i}" for i in range(1, 6)] if train
                 else ["test_batch"])
        raw = _load_cifar_batches(root, files, b"labels")
        mean, std = _CIFAR10_MEAN, _CIFAR10_STD
    else:
        root = data_dir() / "cifar-100-python"
        raw = _load_cifar_batches(root, ["train" if train else "test"],
                                  b"fine_labels")
        mean, std = _CIFAR100_MEAN, _CIFAR100_STD
    if raw is not None:
        x, y = raw
        x = (x.astype(np.float32) / 255.0 - mean) / std
        return ArrayDataset(x, y)
    n = synthetic_size or (10000 if train else 2000)
    return _synthetic_images(n, (32, 32, 3), n_classes,
                             seed=100 + n_classes, train=train)


@register_dataset("CIFAR10")
def cifar10(train: bool = True, synthetic_size: int | None = None):
    return _cifar(train, synthetic_size, 10)


@register_dataset("CIFAR100")
def cifar100(train: bool = True, synthetic_size: int | None = None):
    return _cifar(train, synthetic_size, 100)


@register_dataset("MNIST")
def mnist(train: bool = True, synthetic_size: int | None = None):
    root = data_dir() / "MNIST" / "raw"
    stem = "train" if train else "t10k"
    img_p = root / f"{stem}-images-idx3-ubyte"
    lbl_p = root / f"{stem}-labels-idx1-ubyte"
    if img_p.exists() and lbl_p.exists():
        with open(img_p, "rb") as f:
            f.read(16)
            x = np.frombuffer(f.read(), np.uint8).reshape(-1, 28, 28, 1)
        with open(lbl_p, "rb") as f:
            f.read(8)
            y = np.frombuffer(f.read(), np.uint8).astype(np.int32)
        x = (x.astype(np.float32) / 255.0 - 0.1307) / 0.3081
        return ArrayDataset(x, y)
    n = synthetic_size or (10000 if train else 2000)
    return _synthetic_images(n, (28, 28, 1), 10, seed=200,
                             train=train)


# --------------------------------------------------------------------------
# AG-News / Emotion (token classification)
# --------------------------------------------------------------------------

_AGNEWS_SEQ_LEN = 128  # reference fixed length, src/dataset/AGNEWS.py:21
_BERT_VOCAB = 28996


def _hash_tokenize(texts: list[str], seq_len: int, vocab: int) -> np.ndarray:
    """Deterministic offline tokenizer: whitespace split + stable hash into
    the BERT vocab range.  Used when no pretrained tokenizer files exist on
    disk (zero egress); real runs can drop a HF tokenizer under data/."""
    import zlib
    out = np.zeros((len(texts), seq_len), np.int32)
    for i, t in enumerate(texts):
        ids = [101]  # [CLS]
        for w in t.lower().split()[:seq_len - 2]:
            ids.append(1000 + zlib.crc32(w.encode()) % (vocab - 1100))
        ids.append(102)  # [SEP]
        out[i, :len(ids)] = ids[:seq_len]
    return out


def _agnews_csv(path: pathlib.Path) -> tuple | None:
    if not path.exists():
        return None
    texts, labels = [], []
    with open(path, newline="", encoding="utf-8") as f:
        for row in csv.reader(f):
            if len(row) < 3:
                continue
            labels.append(int(row[0]) - 1)
            texts.append(row[1] + " " + row[2])
    return texts, np.asarray(labels, np.int32)


def _tokenize(texts: list[str], seq_len: int, vocab: int) -> np.ndarray:
    """Real WordPiece when a pretrained vocab.txt is on disk (token ids
    then match the reference's BertTokenizer, ``src/dataset/AGNEWS.py:
    13-30``); deterministic hash tokenization otherwise (zero egress)."""
    from split_learning_tpu.data.wordpiece import (
        WordPieceTokenizer, find_vocab,
    )
    vocab_path = find_vocab(data_dir())
    if vocab_path is not None:
        tok = WordPieceTokenizer.from_file(vocab_path)
        if max(tok.vocab.values(), default=0) >= vocab:
            # e.g. an uncased 30522-entry vocab.txt against the 28996
            # cased embedding table: out-of-range ids would be silently
            # clamped by the embedding gather under jit
            raise ValueError(
                f"{vocab_path} holds token ids up to "
                f"{max(tok.vocab.values())} but the model's embedding "
                f"table holds {vocab}; use the matching (cased) vocab")
        return tok.encode_batch(texts, seq_len)
    return _hash_tokenize(texts, seq_len, vocab)


@register_dataset("AGNEWS")
def agnews(train: bool = True, synthetic_size: int | None = None,
           vocab: int = _BERT_VOCAB):
    raw = _agnews_csv(data_dir() / "ag_news"
                      / ("train.csv" if train else "test.csv"))
    if raw is not None:
        texts, labels = raw
        ids = _tokenize(texts, _AGNEWS_SEQ_LEN, vocab)
        return ArrayDataset(ids, labels)
    n = synthetic_size or (8000 if train else 1600)
    return _synthetic_tokens(n, _AGNEWS_SEQ_LEN, vocab, 4,
                             seed=300 + (0 if train else 1))


_EMOTION_LABELS = {"sadness": 0, "joy": 1, "love": 2, "anger": 3,
                   "fear": 4, "surprise": 5}


def _emotion_file(path: pathlib.Path) -> tuple | None:
    """dair-ai emotion distribution format: one ``text;label`` per line
    (label a name or an int).  Also accepts 2-column CSV."""
    if not path.exists():
        return None
    def parse_label(lab: str):
        lab = lab.strip().lower()
        idx = _EMOTION_LABELS.get(lab) if not lab.isdigit() else int(lab)
        return idx if idx is not None and 0 <= idx < 6 else None

    texts, labels = [], []
    for line in path.read_text(encoding="utf-8").splitlines():
        if not line.strip():
            continue
        text = idx = None
        if ";" in line:
            cand, _, lab = line.rpartition(";")
            idx = parse_label(lab)
            text = cand
        if idx is None:
            # quoted CSV whose text itself contains ';' lands here
            row = next(csv.reader([line]))
            if len(row) >= 2:
                idx = parse_label(row[-1])
                text = ",".join(row[:-1])
        if idx is None:
            continue
        texts.append(text)
        labels.append(idx)
    return (texts, np.asarray(labels, np.int32)) if texts else None


@register_dataset("EMOTION")
def emotion(train: bool = True, synthetic_size: int | None = None,
            vocab: int = _BERT_VOCAB):
    """6-label emotion set (Vanilla_SL BERT_EMOTION variant).

    On-disk: ``data/emotion/{train,test}.{txt,csv}`` in the dair-ai
    ``text;label`` line format; tokenized like AGNEWS (real WordPiece
    when a vocab.txt is present, hash fallback otherwise).  The
    reference ships the 6-label BERT_EMOTION model
    (``other/Vanilla_SL/src/model/BERT_EMOTION.py:6-7``) but no loader
    for it; this completes the path."""
    stem = "train" if train else "test"
    for ext in ("txt", "csv"):
        raw = _emotion_file(data_dir() / "emotion" / f"{stem}.{ext}")
        if raw is not None:
            texts, labels = raw
            ids = _tokenize(texts, _AGNEWS_SEQ_LEN, vocab)
            return ArrayDataset(ids, labels)
    n = synthetic_size or (8000 if train else 1600)
    return _synthetic_tokens(n, _AGNEWS_SEQ_LEN, vocab, 6,
                             seed=400 + (0 if train else 1))


@register_dataset("TINYSTORIES")
def tinystories(train: bool = True, synthetic_size: int | None = None,
                seq_len: int = 257, vocab: int = 32000):
    """Causal-LM token streams (north-star TinyLlama config).

    On-disk: ``data/TinyStories/{train,valid}.npy`` of shape (N, seq_len)
    int32 token ids; otherwise synthetic Markov-ish token sequences.
    Inputs are ids[:, :-1]; labels the next-token shift ids[:, 1:]."""
    path = (data_dir() / "TinyStories"
            / ("train.npy" if train else "valid.npy"))
    if path.exists():
        ids = np.load(path).astype(np.int32)
    else:
        n = synthetic_size or (4000 if train else 400)
        rng = np.random.default_rng(500 + (0 if train else 1))
        # band-structured transitions so a real LM can reduce loss; the
        # band width scales down for tiny test vocabs
        band = max(1, min(32, vocab // 4))
        starts = rng.integers(0, max(1, vocab - 2 * band), size=(n, 1))
        steps = rng.integers(-band, band + 1,
                             size=(n, seq_len - 1)).cumsum(axis=1)
        ids = np.clip(starts + np.concatenate(
            [np.zeros((n, 1), np.int64), steps], axis=1), 0, vocab - 1)
        ids = ids.astype(np.int32)
    return ArrayDataset(ids[:, :-1], ids[:, 1:].astype(np.int32))


# --------------------------------------------------------------------------
# SpeechCommands (MFCC)
# --------------------------------------------------------------------------

_SC_CLASSES = ["yes", "no", "up", "down", "left", "right", "on", "off",
               "stop", "go"]  # 10-class subset, SPEECHCOMMANDS.py:60-91


@register_dataset("SPEECHCOMMANDS")
def speechcommands(train: bool = True, synthetic_size: int | None = None):
    root = data_dir() / "SpeechCommands" / "speech_commands_v0.02"
    if root.exists():
        from split_learning_tpu.data.mfcc import mfcc_batch
        split_files: set[str] = set()
        for listing in ("validation_list.txt", "testing_list.txt"):
            p = root / listing
            if p.exists():
                split_files |= set(p.read_text().split())
        signals, labels = [], []
        for ci, cls in enumerate(_SC_CLASSES):
            for wav in sorted((root / cls).glob("*.wav")):
                rel = f"{cls}/{wav.name}"
                if train == (rel in split_files):
                    continue
                sig = _read_wav_mono(wav)
                signals.append(
                    np.pad(sig, (0, max(0, 16000 - len(sig))))[:16000])
                labels.append(ci)
        if signals:
            # one batched call: hits the native C++ extractor when built
            return ArrayDataset(mfcc_batch(np.stack(signals)),
                                np.asarray(labels, np.int32))
    # synthetic MFCC-shaped blobs: (40, 98) like a 1 s 16 kHz clip
    n = synthetic_size or (4000 if train else 800)
    return _synthetic_images(n, (40, 98), 10, seed=500,
                             train=train)


def _read_wav_mono(path: pathlib.Path) -> np.ndarray:
    import wave
    with wave.open(str(path), "rb") as w:
        raw = w.readframes(w.getnframes())
        x = np.frombuffer(raw, dtype=np.int16).astype(np.float32) / 32768.0
        if w.getnchannels() > 1:
            x = x.reshape(-1, w.getnchannels()).mean(axis=1)
    return x


# --------------------------------------------------------------------------
# dispatcher — reference parity: data_loader(name, bs, distribution, train)
# --------------------------------------------------------------------------

def make_data_loader(name: str, batch_size: int,
                     distribution: np.ndarray | None = None,
                     train: bool = True, seed: int = 0,
                     synthetic_size: int | None = None,
                     dataset_kwargs: dict | None = None) -> DataLoader:
    """``distribution`` is the per-label sample-count vector a client was
    assigned (``src/Server.py:87-101``); None = the full set."""
    ds = get_dataset(name, train=train, synthetic_size=synthetic_size,
                     **(dataset_kwargs or {}))
    if distribution is not None:
        rng = np.random.default_rng(seed)
        if np.ndim(ds.labels) > 1:
            # sequence labels (causal LM): class counts are meaningless —
            # take a random subset of the requested total size instead,
            # wrapping with replacement like label_count_subset does
            total = max(1, int(np.sum(distribution)))
            idx = rng.choice(len(ds), size=total,
                             replace=total > len(ds))
        else:
            idx = label_count_subset(ds.labels, distribution, rng)
        ds = ds.take(idx)
    augment = cifar_augment if (train and name in ("CIFAR10", "CIFAR100")) \
        else None
    return DataLoader(ds, batch_size, shuffle=train, augment=augment,
                      seed=seed)
