"""MFCC feature extraction in pure numpy.

Parity with the reference's manual pipeline
(``/root/reference/src/dataset/SPEECHCOMMANDS.py:11-47``): pre-emphasis,
25 ms / 10 ms framing, Hamming window, power spectrum, mel filterbank,
log, DCT-II with ortho norm — yielding (n_mfcc, n_frames) = (40, 98) for a
1-second 16 kHz clip.  Vectorized over frames (the reference loops); a
C++ drop-in lives in :mod:`split_learning_tpu.native` when built.
"""

from __future__ import annotations

import numpy as np


def _hz_to_mel(hz):
    return 2595.0 * np.log10(1.0 + np.asarray(hz) / 700.0)


def _mel_to_hz(mel):
    return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)


def mel_filterbank(n_filters: int, n_fft: int, sample_rate: int,
                   f_min: float = 0.0,
                   f_max: float | None = None) -> np.ndarray:
    """(n_filters, n_fft//2 + 1) triangular mel filterbank."""
    f_max = f_max if f_max is not None else sample_rate / 2.0
    mels = np.linspace(_hz_to_mel(f_min), _hz_to_mel(f_max), n_filters + 2)
    hz = _mel_to_hz(mels)
    bins = np.floor((n_fft + 1) * hz / sample_rate).astype(int)
    fb = np.zeros((n_filters, n_fft // 2 + 1))
    for m in range(1, n_filters + 1):
        lo, ctr, hi = bins[m - 1], bins[m], bins[m + 1]
        for k in range(lo, ctr):
            if ctr > lo:
                fb[m - 1, k] = (k - lo) / (ctr - lo)
        for k in range(ctr, hi):
            if hi > ctr:
                fb[m - 1, k] = (hi - k) / (hi - ctr)
    return fb


def _dct_ortho(x: np.ndarray, n_out: int) -> np.ndarray:
    """DCT-II along the last axis with ortho normalization."""
    n = x.shape[-1]
    k = np.arange(n_out)[:, None]
    i = np.arange(n)[None, :]
    basis = np.cos(np.pi * k * (2 * i + 1) / (2 * n))
    scale = np.full((n_out, 1), np.sqrt(2.0 / n))
    scale[0, 0] = np.sqrt(1.0 / n)
    return x @ (basis * scale).T


def compute_mfcc(signal: np.ndarray, sample_rate: int = 16000,
                 n_mfcc: int = 40, frame_ms: float = 25.0,
                 hop_ms: float = 10.0, n_fft: int = 512,
                 n_mels: int = 64, pre_emphasis: float = 0.97,
                 eps: float = 1e-10) -> np.ndarray:
    """(n_mfcc, n_frames) MFCCs of a mono signal."""
    sig = np.asarray(signal, dtype=np.float64)
    sig = np.append(sig[0], sig[1:] - pre_emphasis * sig[:-1])

    frame_len = int(round(sample_rate * frame_ms / 1000.0))
    hop = int(round(sample_rate * hop_ms / 1000.0))
    n_frames = max(1, 1 + (len(sig) - frame_len) // hop)
    pad = (n_frames - 1) * hop + frame_len - len(sig)
    if pad > 0:
        sig = np.pad(sig, (0, pad))
    idx = (np.arange(frame_len)[None, :]
           + hop * np.arange(n_frames)[:, None])
    frames = sig[idx] * np.hamming(frame_len)[None, :]

    spec = np.abs(np.fft.rfft(frames, n=n_fft, axis=1)) ** 2 / n_fft
    fb = mel_filterbank(n_mels, n_fft, sample_rate)
    mel_energy = np.log(spec @ fb.T + eps)
    mfcc = _dct_ortho(mel_energy, n_mfcc)
    return mfcc.T.astype(np.float32)  # (n_mfcc, n_frames)


_NATIVE_OK: bool | None = None   # None = untried; False = failed once


def mfcc_batch(signals: np.ndarray, **kw) -> np.ndarray:
    """(B, n_mfcc, n_frames) over a batch of equal-length signals.

    Prefers the native C++ extractor when a compiler is available;
    numerically interchangeable with the numpy pipeline.  A failed build
    or an unsupported kwarg (e.g. ``eps``) disables the native path for
    the process rather than retrying the compile per call."""
    global _NATIVE_OK
    if _NATIVE_OK is not False:
        try:
            from split_learning_tpu.native import mfcc_batch_native
            out = mfcc_batch_native(np.asarray(signals), **kw)
            _NATIVE_OK = True
            return out
        except Exception:   # no compiler / load failure / kwarg mismatch
            _NATIVE_OK = False
    return np.stack([compute_mfcc(s, **kw) for s in signals])
