"""Datasets, loaders, and feature pipelines.

Reference parity: ``/root/reference/src/dataset/`` (dataloader dispatch,
AGNEWS tokenization, SpeechCommands MFCC) with on-disk loading plus
deterministic synthetic fallbacks for the zero-egress environment.
"""

from split_learning_tpu.data.loader import (
    ArrayDataset, DataLoader, cifar_augment, label_count_subset, subset_seed,
)
from split_learning_tpu.data.datasets import (
    get_dataset, make_data_loader, register_dataset, dataset_registry,
)

__all__ = [
    "ArrayDataset", "DataLoader", "cifar_augment", "label_count_subset", "subset_seed",
    "get_dataset", "make_data_loader", "register_dataset",
    "dataset_registry",
]
