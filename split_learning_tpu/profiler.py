"""Offline device/model profiler feeding the partition planner.

Parity surface (``/root/reference/profiling.py``): per-layer forward
execution times (``:22-44`` pre/post hooks, ``:68-73`` timed pass),
per-layer activation byte sizes (``:38``), device speed = batch /
total-time (``:77``), and a network bandwidth probe publishing 1–9 MB
payloads and timing them (``:80-109``); results written to
``profiling.json`` (``:111-121``) and embedded in REGISTER
(``client.py:52-59``).

TPU-native differences:

* activation sizes come from ``jax.eval_shape`` — exact, no execution;
* per-layer cost has two modes: ``"time"`` (jitted per-layer apply,
  wall-clock median — the reference's method, right for real hardware)
  and ``"flops"`` (XLA cost analysis of the compiled layer — instant and
  noise-free; the planner only needs *relative* costs, so this is the
  default for CI/virtual devices);
* the bandwidth probe times a publish+get round trip through a real
  :class:`~split_learning_tpu.runtime.bus.Transport` rather than a bare
  AMQP publish.

Output keys {exe_time, size_data, speed, network} are exactly what the
planner consumes (``runtime/plan.py`` → ``planner/partition.py``,
reference ``src/Server.py:115-117`` → ``src/Partition.py``).
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from split_learning_tpu.models import build_model, shard_params


def _slice_vars(variables: dict, specs, i: int) -> dict:
    """Layer i's slice of every variable collection."""
    return {col: shard_params(tree, specs, i - 1, i)
            for col, tree in variables.items()}


def _boundary_structs(model_key: str, example: jax.ShapeDtypeStruct,
                      model_kwargs: dict | None):
    """Chained eval_shape: (boundary structs, single-layer models, full
    model)."""
    kw = dict(model_kwargs or {})
    full = build_model(model_key, **kw)
    var_shapes = jax.eval_shape(
        lambda: full.init(jax.random.key(0),
                          jnp.zeros(example.shape, example.dtype),
                          train=False))
    layer_models = [
        build_model(model_key, start_layer=i - 1, end_layer=i, **kw)
        for i in range(1, len(full.specs) + 1)
    ]
    bounds = [example]
    for i, m in enumerate(layer_models, start=1):
        out = jax.eval_shape(lambda v, x, m=m: m.apply(v, x, train=False),
                             _slice_vars(var_shapes, full.specs, i),
                             bounds[-1])
        bounds.append(out)
    return bounds, layer_models, full


def profile_model(model_key: str, batch_size: int = 32,
                  model_kwargs: dict | None = None,
                  example: jax.ShapeDtypeStruct | None = None,
                  method: str = "flops", warmup: int = 2,
                  repeats: int = 5, seed: int = 0) -> dict:
    """Per-layer cost + activation-size profile of a registered model.

    Returns ``{exe_time, size_data, speed, network}`` (network filled by
    :func:`profile_network`; 0.0 here).  ``exe_time`` is seconds in
    ``"time"`` mode and normalized FLOP-seconds-equivalent (flops / 1e12)
    in ``"flops"`` mode — the partition search is scale-invariant
    (``src/Partition.py:2-21`` compares only ratios).
    """
    kw = dict(model_kwargs or {})
    if example is None:
        from split_learning_tpu.data import make_data_loader
        from split_learning_tpu.runtime.validation import (
            dataset_for_model, dataset_kwargs_for_model,
        )
        ds = make_data_loader(dataset_for_model(model_key), 1, train=False,
                              synthetic_size=8,
                              dataset_kwargs=dataset_kwargs_for_model(
                                  model_key, model_kwargs))
        x0, _ = next(iter(ds))
        arr = np.asarray(x0)
        example = jax.ShapeDtypeStruct((batch_size,) + arr.shape[1:],
                                       arr.dtype)

    if method not in ("flops", "time"):
        raise ValueError(f"unknown method {method!r}")
    bounds, layer_models, full = _boundary_structs(model_key, example, kw)
    specs = full.specs
    # a boundary may be a pytree (e.g. BERT's (hidden, mask)): bytes sum
    # over leaves.  Float leaves are recorded at fp32 size whatever the
    # model's native dtype: the wire codec casts every float payload to
    # the configured wire dtype (fp32 default), so what crosses per hop
    # is float_elems x wire_itemsize — the planner applies the
    # wire-dtype ratio at plan time (runtime/plan.py) against this
    # fp32-equivalent record
    size_data = [
        sum(int(np.prod(leaf.shape))
            * (4 if jnp.issubdtype(leaf.dtype, jnp.floating)
               else np.dtype(leaf.dtype).itemsize)
            for leaf in jax.tree_util.tree_leaves(b))
        for b in bounds[1:]
    ]

    variables = full.init(jax.random.key(seed),
                          jnp.zeros(example.shape, example.dtype),
                          train=False)

    exe_time: list[float] = []
    for i, m in enumerate(layer_models, start=1):
        sub = _slice_vars(variables, specs, i)
        x_in = jnp.zeros(bounds[i - 1].shape, bounds[i - 1].dtype)
        fn = jax.jit(lambda v, x, m=m: m.apply(v, x, train=False))
        if method == "flops":
            cost = fn.lower(sub, x_in).compile().cost_analysis()
            if isinstance(cost, (list, tuple)):   # jax < 0.5 spelling
                cost = cost[0] if cost else {}
            flops = float((cost or {}).get("flops", 0.0))
            # param-free reshapes report 0 flops; floor at bytes-touched
            # so no layer is free (the planner divides by these)
            floor = size_data[i - 1] * 1e-3
            exe_time.append(max(flops, floor) / 1e12)
        else:
            out = fn(sub, x_in)
            jax.block_until_ready(out)
            for _ in range(warmup):
                jax.block_until_ready(fn(sub, x_in))
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(sub, x_in))
                ts.append(time.perf_counter() - t0)
            exe_time.append(float(np.median(ts)))

    # speed is ALWAYS wall-clock samples/sec of the full forward (the GMM
    # straggler selection compares speeds ACROSS devices — flop counts are
    # hardware-independent and would make selection a silent no-op)
    x_full = jnp.zeros(example.shape, example.dtype)
    full_fn = jax.jit(lambda v, x: full.apply(v, x, train=False))
    jax.block_until_ready(full_fn(variables, x_full))
    ts = []
    for _ in range(max(2, repeats // 2)):
        t0 = time.perf_counter()
        jax.block_until_ready(full_fn(variables, x_full))
        ts.append(time.perf_counter() - t0)
    speed = float(example.shape[0] / max(float(np.median(ts)), 1e-9))

    return {
        "exe_time": exe_time,
        "size_data": size_data,
        "speed": speed,
        "network": 0.0,
    }


def profile_network(transport, sizes_mb: Sequence[int] = range(1, 10),
                    repeats: int = 5,
                    queue: str = "bandwidth_probe") -> float:
    """Bytes/sec through the transport (``profiling.py:80-109``: 1–9 MB
    payloads, averaged)."""
    rates = []
    for mb in sizes_mb:
        payload = b"\x00" * (mb * 1_000_000)
        for _ in range(repeats):
            t0 = time.perf_counter()
            transport.publish(queue, payload)
            got = transport.get(queue, timeout=30.0)
            dt = time.perf_counter() - t0
            if got is None:
                # the in-flight payload would surface as a stale message
                # and corrupt the next sample's timing — drop it
                transport.purge([queue])
                continue
            rates.append(len(payload) * 2 / dt)   # round trip: 2x bytes
    return float(np.mean(rates)) if rates else 0.0


def write_profile(path: str, profile: dict) -> None:
    with open(path, "w") as f:
        json.dump(profile, f)


def main(argv=None):
    from split_learning_tpu.platform import apply_platform_env
    apply_platform_env()
    ap = argparse.ArgumentParser(
        description="Profile a model + link for the partition planner "
                    "(reference profiling.py parity).")
    ap.add_argument("--config", default="config.yaml")
    ap.add_argument("--output", default="profiling.json")
    ap.add_argument("--method", choices=["flops", "time"], default="time")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--probe-network", action="store_true",
                    help="also measure transport bandwidth (needs broker)")
    args = ap.parse_args(argv)

    from split_learning_tpu.config import from_yaml
    cfg = from_yaml(args.config)
    prof = profile_model(
        cfg.model_key, batch_size=args.batch or cfg.learning.batch_size,
        model_kwargs=dict(cfg.model_kwargs or {}), method=args.method)
    if args.probe_network:
        from split_learning_tpu.runtime.bus import make_transport
        bus = make_transport(cfg.transport.kind, cfg.transport.host,
                             cfg.transport.port,
                             shards=cfg.broker.shards)
        prof["network"] = profile_network(bus)
        bus.close()
    write_profile(args.output, prof)
    print(json.dumps({"layers": len(prof["exe_time"]),
                      "speed": prof["speed"],
                      "network": prof["network"]}))


if __name__ == "__main__":
    main()
