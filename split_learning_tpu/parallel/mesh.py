"""Device-mesh construction from a cluster plan.

The reference maps (cluster, client, stage) onto RabbitMQ queue names
(``src/train/VGG16.py:21-22``, ``43-44``); here the same coordinates become
axes of a ``jax.sharding.Mesh``.  One cluster = one mesh of shape
(client, stage); clusters with different cut points compile different
pipeline programs and run on disjoint device sub-slices.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_clients: int, n_stages: int,
              devices: Sequence | None = None,
              tensor_parallel: int = 1,
              seq_parallel: int = 1,
              expert_parallel: int = 1) -> Mesh:
    """Mesh of shape (client, stage[, model|seq|expert]) over the first
    n_clients*n_stages*(third-axis width) devices.

    With ``tensor_parallel > 1`` a third ``model`` axis is appended:
    each (client, stage) cell becomes a TP group whose parameters shard
    over ``model`` under the GSPMD rules in
    :mod:`split_learning_tpu.parallel.tensor` — pipeline collectives
    stay manual over ``stage`` while XLA derives the TP collectives
    (the PP x TP composition the reference's per-stage torch clients
    cannot express, ``src/Server.py:222-228``).

    With ``seq_parallel > 1`` the third axis is ``seq`` instead: each
    (client, stage) cell becomes a ring-attention group — stage hops
    (manual ppermute over ``stage``) move per-device SEQUENCE BLOCKS,
    and attention inside every stage rotates K/V around ``seq``
    (:func:`split_learning_tpu.parallel.sequence.ring_attention`).

    With ``expert_parallel > 1`` it is ``expert``: MoE expert
    parameters shard over the axis (GSPMD-auto, like ``model``) and
    XLA derives the dispatch/combine all-to-alls inside each stage
    (:mod:`split_learning_tpu.parallel.expert`)."""
    devs = list(devices if devices is not None else jax.devices())
    widths = {"model": tensor_parallel, "seq": seq_parallel,
              "expert": expert_parallel}
    extra = [(k, v) for k, v in widths.items() if v > 1]
    if len(extra) > 1:
        raise ValueError(
            f"only one intra-stage axis may exceed 1 in one pipeline "
            f"mesh, got {dict(extra)}")
    third = extra[0] if extra else None
    need = n_clients * n_stages * (third[1] if third else 1)
    if len(devs) < need:
        raise ValueError(
            f"need {need} devices for mesh (client={n_clients}, "
            f"stage={n_stages}"
            + (f", {third[0]}={third[1]}" if third else "")
            + f"), have {len(devs)}")
    if third:
        grid = np.array(devs[:need]).reshape(n_clients, n_stages,
                                             third[1])
        return Mesh(grid, ("client", "stage", third[0]))
    grid = np.array(devs[:need]).reshape(n_clients, n_stages)
    return Mesh(grid, ("client", "stage"))


def stage_ranges(n_layers: int, cuts: Sequence[int]) -> list[tuple[int, int]]:
    """Turn 1-based cut layers into per-stage (start, end) layer ranges.

    ``cuts=[7]`` over 52 layers -> ``[(0, 7), (7, 52)]`` — stage k owns
    layers ``start+1..end``, the same contract as the reference's START
    message ``layers`` ranges (``src/Server.py:221-228``).
    """
    if any(not (1 <= c < n_layers) for c in cuts):
        raise ValueError(
            f"cuts {cuts!r} out of range [1, {n_layers - 1}]")
    bounds = [0] + sorted(cuts) + [n_layers]
    if len(set(bounds)) != len(bounds):
        raise ValueError(f"degenerate cuts {cuts!r} for {n_layers} layers")
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]
