"""Device-mesh construction from a cluster plan.

The reference maps (cluster, client, stage) onto RabbitMQ queue names
(``src/train/VGG16.py:21-22``, ``43-44``); here the same coordinates become
axes of a ``jax.sharding.Mesh``.  One cluster = one mesh of shape
(client, stage); clusters with different cut points compile different
pipeline programs and run on disjoint device sub-slices.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_clients: int, n_stages: int,
              devices: Sequence | None = None,
              tensor_parallel: int = 1) -> Mesh:
    """Mesh of shape (client, stage[, model]) over the first
    n_clients*n_stages*tensor_parallel devices.

    With ``tensor_parallel > 1`` a third ``model`` axis is appended:
    each (client, stage) cell becomes a TP group whose parameters shard
    over ``model`` under the GSPMD rules in
    :mod:`split_learning_tpu.parallel.tensor` — pipeline collectives
    stay manual over ``stage`` while XLA derives the TP collectives
    (the PP x TP composition the reference's per-stage torch clients
    cannot express, ``src/Server.py:222-228``)."""
    devs = list(devices if devices is not None else jax.devices())
    need = n_clients * n_stages * tensor_parallel
    if len(devs) < need:
        raise ValueError(
            f"need {need} devices for mesh (client={n_clients}, "
            f"stage={n_stages}"
            + (f", model={tensor_parallel}" if tensor_parallel > 1
               else "")
            + f"), have {len(devs)}")
    if tensor_parallel > 1:
        grid = np.array(devs[:need]).reshape(n_clients, n_stages,
                                             tensor_parallel)
        return Mesh(grid, ("client", "stage", "model"))
    grid = np.array(devs[:need]).reshape(n_clients, n_stages)
    return Mesh(grid, ("client", "stage"))


def stage_ranges(n_layers: int, cuts: Sequence[int]) -> list[tuple[int, int]]:
    """Turn 1-based cut layers into per-stage (start, end) layer ranges.

    ``cuts=[7]`` over 52 layers -> ``[(0, 7), (7, 52)]`` — stage k owns
    layers ``start+1..end``, the same contract as the reference's START
    message ``layers`` ranges (``src/Server.py:221-228``).
    """
    if any(not (1 <= c < n_layers) for c in cuts):
        raise ValueError(
            f"cuts {cuts!r} out of range [1, {n_layers - 1}]")
    bounds = [0] + sorted(cuts) + [n_layers]
    if len(set(bounds)) != len(bounds):
        raise ValueError(f"degenerate cuts {cuts!r} for {n_layers} layers")
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]
