"""Mesh planning and the compiled split-learning pipeline runtime."""

from split_learning_tpu.parallel.mesh import make_mesh, stage_ranges
from split_learning_tpu.parallel.pipeline import (
    PipelineModel, make_train_step, make_fedavg_step,
)

__all__ = [
    "make_mesh", "stage_ranges", "PipelineModel", "make_train_step",
    "make_fedavg_step",
]
