"""Mesh planning, the compiled split-learning pipeline runtime, and the
sequence/tensor parallel primitives."""

from split_learning_tpu.parallel.mesh import make_mesh, stage_ranges
from split_learning_tpu.parallel.pipeline import (
    PipelineModel, StageParamLayout, make_fedavg_step,
    make_sliced_train_step, make_train_step, shard_sliced_opt_to_mesh,
    slice_params_for_mesh,
)
from split_learning_tpu.parallel.sequence import (
    make_ring_attention_fn, ring_attention, ulysses_attention,
)
from split_learning_tpu.parallel.tensor import (
    make_tp_train_step, shard_params_tp, tp_shardings, tp_spec,
)
from split_learning_tpu.parallel.expert import (
    make_ep_train_step, shard_params_ep,
)
from split_learning_tpu.parallel.zero import (
    adamw_bf16_states, init_zero1_opt_state, make_zero1_train_step,
)

__all__ = [
    "make_mesh", "stage_ranges", "PipelineModel", "StageParamLayout",
    "make_train_step", "make_sliced_train_step", "slice_params_for_mesh",
    "shard_sliced_opt_to_mesh",
    "make_fedavg_step", "ring_attention", "ulysses_attention",
    "make_ring_attention_fn", "make_tp_train_step", "shard_params_tp",
    "tp_shardings", "tp_spec", "make_ep_train_step", "shard_params_ep",
    "adamw_bf16_states", "init_zero1_opt_state", "make_zero1_train_step",
]
