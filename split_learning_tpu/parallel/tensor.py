"""Tensor parallelism: GSPMD param-sharding rules over a ``model`` axis.

The reference has no tensor parallelism (SURVEY.md §2.2) — this is the
fresh TPU-native design: instead of manual collectives, parameters are
annotated with Megatron-style ``PartitionSpec``s and ``jit`` lets XLA
insert the all-gathers/reduce-scatters (the GSPMD recipe from the
scaling-book):

* column-parallel kernels (q/k/v, FFN up/gate) shard their OUTPUT dim —
  the following elementwise work stays local;
* row-parallel kernels (attention out, FFN down) shard their INPUT dim —
  XLA emits one psum after the matmul pair;
* embeddings shard the feature dim; norms/bias-only layers replicate.

Annotations are layout hints, not math: a miss-listed layer still
computes correctly, it just replicates.  The rules operate on param-path
names, so they compose with the split-layer models (a shard's subtree
annotates the same way) and stack with the (cluster, client, stage)
mesh axes — TP is just one more axis in the mesh tuple.

TP composes with the pipeline's REPLICATED parameter layout only: the
stage-sliced flat wire (``pipeline.make_sliced_train_step``) erases the
param-path names these rules key on, so a cut model picks one
residency tool per axis — slice along ``stage`` (1/A of the model per
device, elementwise optimizers) or shard along ``model`` (per-leaf
Megatron specs, any optimizer), not both on the same leaves.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: kernels whose OUTPUT dim is sharded (column parallel)
COLUMN_PARALLEL = frozenset({
    "query", "key", "value", "q_proj", "k_proj", "v_proj",
    "gate_proj", "up_proj", "intermediate", "mlp_in",
})
#: kernels whose INPUT dim is sharded (row parallel)
ROW_PARALLEL = frozenset({
    "out", "o_proj", "down_proj", "output", "mlp_out",
})


def _names(path) -> list:
    out = []
    for p in path:
        out.append(str(p.key) if hasattr(p, "key") else str(p))
    return out


def tp_spec(path, leaf, axis: str = "model") -> P:
    """PartitionSpec for one param leaf under tensor parallelism."""
    names = _names(path)
    ndim = np.ndim(leaf)
    leafname = names[-1] if names else ""
    in_col = any(n in COLUMN_PARALLEL for n in names)
    in_row = any(n in ROW_PARALLEL for n in names)
    if leafname == "kernel" and ndim >= 2:
        if in_col:   # e.g. (in, heads, head_dim) / (in, out): shard out
            return P(*([None] * (ndim - 1) + [axis])) if ndim == 2 \
                else P(None, axis, *([None] * (ndim - 2)))
        if in_row:   # e.g. (heads, head_dim, out) / (in, out): shard in
            return P(axis, *([None] * (ndim - 1)))
    if leafname == "bias" and in_col and ndim >= 1:
        # column-parallel bias lives with the sharded output features
        return P(axis, *([None] * (ndim - 1)))
    if leafname == "embedding" and ndim == 2:
        return P(None, axis)   # features sharded, vocab gather local
    return P()


def tp_shardings(params, mesh: Mesh, axis: str = "model"):
    """NamedSharding pytree for a param tree (pass to device_put or as
    jit in_shardings)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, tp_spec(path, leaf, axis)),
        params)


def shard_params_tp(params, mesh: Mesh, axis: str = "model"):
    """Place a param tree onto the mesh under the TP rules."""
    return jax.tree_util.tree_map(
        jax.device_put, params, tp_shardings(params, mesh, axis))


def make_tp_train_step(model, optimizer, mesh: Mesh,
                       axis: str = "model", dp_axis: str | None = None):
    """Jitted TP(+DP) train step for a full (unsplit) model.

    Params/opt state are TP-sharded; the batch shards over ``dp_axis``
    (replicated if None).  XLA derives every collective: all-gather for
    column-parallel outputs feeding replicated ops, psum closing each
    row-parallel matmul, and the DP gradient mean.
    """
    import jax.numpy as jnp
    import optax

    data_spec = P(dp_axis) if dp_axis else P()
    data_sh = NamedSharding(mesh, data_spec)

    def step(params, opt_state, x, labels, rng):
        def loss_fn(p):
            out = model.apply({"params": p}, x, train=True,
                              rngs={"dropout": rng})
            return optax.softmax_cross_entropy_with_integer_labels(
                out.astype(jnp.float32), labels).mean()
        lval, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, lval

    def place(params, opt_state, x, labels, rng):
        return step(params, opt_state,
                    jax.lax.with_sharding_constraint(x, data_sh),
                    jax.lax.with_sharding_constraint(labels, data_sh),
                    rng)

    return jax.jit(place, donate_argnums=(0, 1))
