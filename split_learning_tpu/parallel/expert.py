"""Expert parallelism: Mixture-of-Experts routing over an ``expert`` axis.

The reference has no expert parallelism (SURVEY.md §2.2 marks EP absent)
— this is a fresh TPU-native extension completing the parallelism
surface (dp/pp/tp/sp/ep).  Design follows the GSPMD recipe rather than
hand-written collectives:

* the MoE layer computes dense ``dispatch``/``combine`` tensors
  (Switch/GShard-style top-k routing with a static per-expert capacity,
  so every shape is known to XLA — no dynamic gather/scatter);
* expert parameters carry a leading ``num_experts`` dim (``nn.vmap``
  over an FFN) and are sharded ``P("expert", ...)``;
* tokens ride the data axis; the two routing einsums
  ``tec,th->ech`` / ``tec,ech->th`` then force XLA to insert the
  expert-parallel all-to-alls on its own — the same collective an
  NCCL MoE implementation would issue by hand, but fused and
  overlapped by the compiler.

Routing math: softmax router in fp32, top-k experts per token with
renormalized gate weights, tokens over capacity dropped (their combine
weight is zero, so they pass through the residual unchanged — standard
Switch semantics).  The load-balance auxiliary loss is sown into the
``intermediates`` collection; :func:`moe_aux_loss` or the bundled
train step adds it to the objective.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def topk_dispatch(probs: jnp.ndarray, k: int, capacity: int):
    """Top-k routing tensors from router probabilities.

    Args:
      probs: (T, E) fp32 router probabilities (rows sum to 1).
      k: experts per token.
      capacity: static per-expert token budget C.

    Returns ``(combine, dispatch, aux)``: combine (T, E, C) fp32 gate
    weights (renormalized over the top-k, zero for dropped tokens),
    dispatch (T, E, C) {0,1} routing mask, and the Switch load-balance
    auxiliary loss ``E * Σ_e f_e · P_e`` over first-choice assignments.
    """
    t, e = probs.shape
    if k > e:
        raise ValueError(
            f"top-k k={k} exceeds num_experts={e}: argmax over the "
            "masked-out remainder would re-select expert 0 and "
            "double-count its gate weight")
    remaining = probs
    onehots, gates = [], []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=1)
        onehot = jax.nn.one_hot(idx, e, dtype=probs.dtype)
        onehots.append(onehot)
        gates.append(jnp.sum(probs * onehot, axis=1))
        remaining = remaining * (1.0 - onehot)

    # renormalize gate weights over the chosen k (Mixtral convention)
    denom = functools.reduce(jnp.add, gates)
    gates = [g / jnp.maximum(denom, 1e-9) for g in gates]

    combine = jnp.zeros((t, e, capacity), probs.dtype)
    dispatch = jnp.zeros((t, e, capacity), probs.dtype)
    prev_counts = jnp.zeros((e,), probs.dtype)
    for onehot, gate in zip(onehots, gates):
        # position of each token within its expert's buffer, counting
        # earlier routing rounds (priority: round 0 fills first)
        pos_all = jnp.cumsum(onehot, axis=0) - 1.0 + prev_counts[None, :]
        pos = jnp.sum(pos_all * onehot, axis=1)
        keep = (pos < capacity).astype(probs.dtype)
        prev_counts = prev_counts + jnp.sum(onehot, axis=0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=probs.dtype)
        d = onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]

    # load balance: fraction routed (first choice) x mean router prob
    frac = jnp.mean(onehots[0], axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return combine, dispatch, aux


class ExpertFFN(nn.Module):
    """One expert: SwiGLU FFN (LLaMA geometry)."""
    hidden_size: int
    intermediate_size: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        dense = functools.partial(nn.Dense, use_bias=False,
                                  dtype=self.dtype)
        gate = nn.silu(dense(self.intermediate_size, name="gate_proj")(x))
        up = dense(self.intermediate_size, name="up_proj")(x)
        return dense(self.hidden_size, name="down_proj")(gate * up)


class MoEMLP(nn.Module):
    """Top-k mixture-of-experts FFN, drop-in for a dense SwiGLU MLP.

    Input/output (B, S, H).  ``capacity_factor`` scales the per-expert
    buffer ``C = ceil(k·T/E · factor)``; tokens over budget are dropped
    (combine weight 0 → they contribute nothing, the caller's residual
    carries them through).  The aux loss is sown under
    ``intermediates/aux_loss`` when that collection is mutable.

    Memory note: the dense dispatch/combine tensors are (T, E, C) with
    C ≈ k·T/E·factor, i.e. O(k·T²·factor) per MoE layer regardless of
    E.  At T = B·S ≈ 16k tokens that is ~GB-scale in fp32; keep
    T ≲ 8k per call (shard the batch/sequence first), or route within
    fixed-size groups (reshape to (G, T/G) and vmap this module over G)
    before scaling further.
    """
    hidden_size: int
    intermediate_size: int
    num_experts: int = 8
    k: int = 2
    capacity_factor: float = 1.5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, h = x.shape
        t = b * s
        xt = x.reshape(t, h)
        logits = nn.Dense(self.num_experts, use_bias=False,
                          dtype=jnp.float32, name="router")(
            xt.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        capacity = max(1, int(np.ceil(
            self.k * t / self.num_experts * self.capacity_factor)))
        combine, dispatch, aux = topk_dispatch(probs, self.k, capacity)
        self.sow("intermediates", "aux_loss", aux)

        # (T,E,C),(T,H) -> (E,C,H): the expert-parallel scatter all-to-all
        expert_in = jnp.einsum("tec,th->ech",
                               dispatch.astype(self.dtype), xt)
        experts = nn.vmap(
            ExpertFFN,
            in_axes=0, out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            axis_size=self.num_experts,
            metadata_params={nn.PARTITION_NAME: "expert"},
        )(hidden_size=self.hidden_size,
          intermediate_size=self.intermediate_size,
          dtype=self.dtype, name="experts")
        expert_out = experts(expert_in)            # (E, C, H)
        # (T,E,C),(E,C,H) -> (T,H): the gather all-to-all
        out = jnp.einsum("tec,ech->th", combine.astype(self.dtype),
                         expert_out)
        return out.reshape(b, s, h)


# --------------------------------------------------------------------------
# sharding rules
# --------------------------------------------------------------------------

def _names(path) -> list:
    return [str(p.key) if hasattr(p, "key") else str(p) for p in path]


def ep_spec(path, leaf, axis: str = "expert") -> P:
    """PartitionSpec for one param leaf under expert parallelism: leaves
    under an ``experts`` vmap scope shard their leading (expert) dim;
    everything else replicates.  Compose with :func:`tp_spec` for
    EP x TP by passing its result for non-expert leaves."""
    ndim = np.ndim(leaf)
    if "experts" in _names(path) and ndim >= 1:
        return P(axis, *([None] * (ndim - 1)))
    return P()


def ep_shardings(params, mesh: Mesh, axis: str = "expert"):
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, ep_spec(path, leaf, axis)),
        params)


def shard_params_ep(params, mesh: Mesh, axis: str = "expert"):
    """Place a param tree onto the mesh under the EP rules."""
    return jax.tree_util.tree_map(
        jax.device_put, params, ep_shardings(params, mesh, axis))


def moe_aux_loss(intermediates: dict) -> jnp.ndarray:
    """Sum the sown ``aux_loss`` entries in an intermediates collection.

    Only leaves whose path contains the key ``aux_loss`` are summed —
    other sown diagnostics (router entropy, attention stats, ...) must
    never silently become a weighted loss term.
    """
    total = jnp.zeros(())
    for path, leaf in jax.tree_util.tree_leaves_with_path(intermediates):
        if any(getattr(p, "key", None) == "aux_loss" for p in path):
            total = total + jnp.sum(leaf)
    return total


def make_ep_train_step(model, optimizer, mesh: Mesh,
                       axis: str = "expert", dp_axis: str | None = None,
                       aux_weight: float = 0.01):
    """Jitted EP(+DP) train step for a full (unsplit) MoE model.

    Expert params stay sharded over ``axis``; the batch shards over
    ``dp_axis``.  XLA derives the dispatch/gather all-to-alls from the
    routing einsums.  The sown load-balance losses are added to the CE
    objective with weight ``aux_weight``.
    """
    import optax

    data_sh = NamedSharding(mesh, P(dp_axis) if dp_axis else P())

    def step(params, opt_state, x, labels, rng):
        def loss_fn(p):
            out, mut = model.apply(
                {"params": p}, x, train=True, rngs={"dropout": rng},
                mutable=["intermediates"])
            ce = optax.softmax_cross_entropy_with_integer_labels(
                out.astype(jnp.float32), labels).mean()
            return ce + aux_weight * moe_aux_loss(
                mut.get("intermediates", {})), ce
        (_, ce), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, ce

    def place(params, opt_state, x, labels, rng):
        return step(params, opt_state,
                    jax.lax.with_sharding_constraint(x, data_sh),
                    jax.lax.with_sharding_constraint(labels, data_sh),
                    rng)

    return jax.jit(place, donate_argnums=(0, 1))
