"""Compiled GPipe-style split-learning pipeline over a (client, stage) mesh.

This module replaces the reference's entire training data plane — the
queue-driven streaming loop with bounded in-flight batches and activation
recomputation (``/root/reference/src/train/VGG16.py:61-191``) — with ONE
jitted SPMD program:

* the per-batch activation hop ``intermediate_queue_{k}_{c}`` /
  ``gradient_queue_{k}_{id}`` becomes ``jax.lax.ppermute`` along the
  ``stage`` mesh axis (ICI, inside the compiled step — no host round-trip);
* the reference's ``control-count`` in-flight cap becomes the microbatch
  count of a static GPipe schedule (``num_microbatches``);
* backward recomputation (``src/train/VGG16.py:89-92``) becomes a
  PER-STAGE ``jax.checkpoint`` policy (see *Remat policy* below);
* the backward pipeline is not hand-written at all: differentiating through
  the scan-of-ppermute forward yields the reverse schedule automatically;
* "clients" of the same stage are rows of the mesh's ``client`` axis —
  their training is embarrassingly parallel between round barriers, and the
  round-end weighted FedAvg (``src/Utils.py:35-66``) is a ``psum`` over the
  ``client`` axis (:func:`make_fedavg_step`).

Heterogeneous stages (a VGG cut gives stages wildly different programs) are
handled with ``lax.switch`` over per-stage branches; activations cross the
wire flattened and padded to the largest boundary so every device runs the
same collective.

**Streamed loss** (default, ``stream_loss=True``): the last stage's
branch computes the per-microbatch loss INSIDE the stage block, every
pipeline tick, and the scan carries one accumulating scalar.  The
``(M, mb, n_out)`` collect-then-cross-entropy buffer of the
materialized-logits path — ~3.9 GB/chip at the baseline5 TinyLlama
geometry, 40% of one chip's HBM — never exists: an LLM head's logits are
consumed in the tick that produces them.  When the final stage is
rematerialized (which the ``wide`` policy picks automatically for
wide-output heads), no per-tick logits residual survives to the backward
pass either.  ``stream_loss=False`` keeps the materialized path as the
parity oracle (``tests/test_pipeline_streamed.py``).

**Remat policy** (``remat=``): ``"all"`` checkpoints every stage (the
old blanket behavior — maximum recompute, minimum residency), ``"none"``
stores every stage's activations, and ``"wide"`` (default) checkpoints
exactly the stages whose per-sample boundary width (max of input and
output) exceeds ``remat_threshold`` — narrow CIFAR-scale stages skip the
~1.3x recompute tax entirely while transformer-scale stages keep the
memory bound.  Booleans still work (``True`` == ``"all"``,
``False`` == ``"none"``).

**Tick-loop unroll** (``scan_unroll="auto"``): XLA:CPU runs a scan's
while-loop body through its sequential thunk executor, where the
conv/matmul kernels lose intra-op threading (measured ~3x on the VGG
step — most of the round-5 "2.1x split overhead", which taxed the M=1
unsplit baseline hardest).  ``auto`` fully unrolls short tick loops on
CPU meshes and keeps the compact scan on accelerators, where the loop
costs nothing and unrolling an A-branch switch per tick only bloats
compile time.

**Parameter residency**: by default parameters are replicated along
``stage`` (each device holds the full model, uses only its stage's
slice; gradients are psum'd over ``stage`` to keep replicas in sync) —
the fully-general path for arbitrary heterogeneous cuts.
:func:`make_sliced_train_step` instead keeps each device's OWN stage
slice only, as a flat ``(client, stage)``-sharded parameter wire
(:class:`StageParamLayout`): per-device params/grads/opt-state drop to
~1/A of the model and the per-step full-tree gradient psum over
``stage`` (A redundant copies of every gradient, every step)
disappears; the full tree is reassembled only at FedAvg / validation /
checkpoint boundaries.  Big homogeneous transformer models can also
shard parameters along ``model`` (tensor parallelism,
:mod:`split_learning_tpu.parallel.tensor`).

Semantic note: the reference steps the optimizer once per in-flight batch
with stale weights (async pipelining); here microbatch gradients are
accumulated into one synchronous update per step — same data consumed per
round, deterministic, and MXU-friendly.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from split_learning_tpu.models import build_model, shard_params
from split_learning_tpu.models.split import SplitModel
from split_learning_tpu.ops.fedavg import fedavg_psum
from split_learning_tpu.parallel.mesh import stage_ranges


def _flat_size(shape: Sequence[int]) -> int:
    return int(np.prod(shape[1:]))  # per-sample, excluding batch dim


def _tree_flat_size(struct_tree) -> int:
    """Total per-sample wire width of a (possibly pytree) boundary."""
    return sum(_flat_size(leaf.shape)
               for leaf in jax.tree_util.tree_leaves(struct_tree))


class PipelineModel:
    """Static description + compiled bodies for one pipelined split model.

    Built once per (model, cuts, microbatch geometry); owns no parameters.
    """

    #: per-sample boundary width (flattened elements) above which the
    #: ``wide`` remat policy checkpoints a stage.  Sized so CIFAR-scale
    #: CNN/ViT cuts (<= 2^16 elements/sample) run remat-free while
    #: token-model stages (seq x hidden, millions/sample) keep the
    #: memory-bounding recompute.
    REMAT_WIDE_THRESHOLD = 65536

    def __init__(self, model_name: str, cuts: Sequence[int],
                 example_input: jax.ShapeDtypeStruct | jnp.ndarray,
                 num_microbatches: int = 4,
                 loss: str = "softmax_cross_entropy",
                 remat: bool | str = "wide",
                 remat_threshold: int | None = None,
                 stream_loss: bool = True,
                 scan_unroll: int | str = "auto",
                 model_kwargs: dict | None = None,
                 moe_aux_weight: float = 0.01,
                 seq_axis: str | None = None):
        self.model_name = model_name
        self.moe_aux_weight = moe_aux_weight
        self.model_kwargs = dict(model_kwargs or {})
        # PP x SP (VERDICT r4 item 4): with ``seq_axis`` set the mesh
        # carries a manual ``seq`` axis, ``example_input`` is the
        # PER-DEVICE sequence block, stage models run ring attention
        # over the axis (RoPE offset by the block index), and the wire
        # hop moves each block independently — packing stays purely
        # local, so cuts and sequence sharding compose with no extra
        # boundary collective.  The loss becomes each device's token-
        # block share; psum over ``seq`` rebuilds exact full-sequence
        # gradients (the ring is exact attention).
        self.seq_axis = seq_axis
        self.full_model: SplitModel = build_model(model_name,
                                                  **self.model_kwargs)
        self.specs = self.full_model.specs
        self.n_layers = len(self.specs)
        self.cuts = list(cuts)
        self.ranges = stage_ranges(self.n_layers, self.cuts)
        self.n_stages = len(self.ranges)
        self.num_microbatches = num_microbatches
        # legacy bool spellings map onto the named policies
        remat = {True: "all", False: "none"}.get(remat, remat)
        if remat not in ("all", "wide", "none"):
            raise ValueError(
                f"remat must be 'all', 'wide', 'none' or a bool; got "
                f"{remat!r}")
        self.remat = remat
        self.remat_threshold = int(self.REMAT_WIDE_THRESHOLD
                                   if remat_threshold is None
                                   else remat_threshold)
        self.stream_loss = bool(stream_loss)
        if scan_unroll != "auto" and not isinstance(scan_unroll, int):
            raise ValueError(
                f"scan_unroll must be 'auto' or an int, got "
                f"{scan_unroll!r}")
        self.scan_unroll = scan_unroll
        self.loss_name = loss

        mk_stage = dict(self.model_kwargs)
        if seq_axis is not None:
            mk_stage["seq_axis"] = seq_axis
        self.stage_models = [
            build_model(model_name, start_layer=a, end_layer=b,
                        **mk_stage)
            for a, b in self.ranges
        ]
        # shape twins WITHOUT the seq axis: boundary eval_shape runs
        # outside shard_map (no axis env), and every layer is
        # shape-preserving w.r.t. the local block, so block-sized
        # boundaries come out identical
        shape_models = (self.stage_models if seq_axis is None else [
            build_model(model_name, start_layer=a, end_layer=b,
                        **self.model_kwargs)
            for a, b in self.ranges
        ])
        self.stage_layer_names = [
            [s.name for s in self.specs[a:b]] for a, b in self.ranges
        ]

        # boundary ShapeDtypeStructs per microbatch, chained via eval_shape
        x = (example_input if isinstance(example_input, jax.ShapeDtypeStruct)
             else jax.ShapeDtypeStruct(example_input.shape,
                                       example_input.dtype))
        self.mb_size = x.shape[0]
        self.boundary: list[jax.ShapeDtypeStruct] = [x]
        var_shapes = jax.eval_shape(
            lambda: self.full_model.init(jax.random.key(0), jnp.zeros(
                x.shape, x.dtype), train=False))
        for m, (a, b) in zip(shape_models, self.ranges):
            sub = {
                col: shard_params(tree, self.specs, a, b)
                for col, tree in var_shapes.items()
            }
            out = jax.eval_shape(
                functools.partial(m.apply, train=False), sub,
                self.boundary[-1])
            self.boundary.append(out)
        out_leaves = jax.tree_util.tree_leaves(self.boundary[-1])
        if len(out_leaves) != 1:
            raise ValueError(
                "the final stage must output a single logits array, got "
                f"a {len(out_leaves)}-leaf pytree")
        self.out_struct = out_leaves[0]
        self.n_out = _flat_size(self.out_struct.shape)
        # wire width: the widest boundary that actually RIDES the wire —
        # the INPUT of every stage (boundary[0..S-1]).  The final output
        # does not hop: it returns through a separate exact-width switch
        # slot on the last device, so an LLM head's logits (S x vocab,
        # ~16x wider than hidden for TinyLlama-1.1B) no longer inflate
        # every ppermute buffer and scan carry.
        self.max_flat = max(_tree_flat_size(b) for b in self.boundary[:-1])
        # wire dtype: float32 carries every boundary exactly (token ids
        # are < 2^24; bf16/f32 activations upcast losslessly; bool masks
        # ride as 0.0/1.0)
        self.wire_dtype = jnp.float32
        # per-stage remat flags from the policy: 'wide' checkpoints a
        # stage iff its widest per-sample boundary (input or output)
        # exceeds the threshold — the blanket 'all' policy taxed every
        # narrow stage with a full recompute it never needed
        widths = [_tree_flat_size(b) for b in self.boundary]
        if self.remat == "all":
            self.stage_remat = [True] * self.n_stages
        elif self.remat == "none":
            self.stage_remat = [False] * self.n_stages
        else:
            self.stage_remat = [
                max(widths[s], widths[s + 1]) > self.remat_threshold
                for s in range(self.n_stages)
            ]
        # full-model param SHAPES (ShapeDtypeStructs) for the flat
        # stage-sliced layout; owns no memory
        self.param_shapes = var_shapes.get("params", {})
        self._layout_cache: dict = {}

    #: auto-unroll bound: tick loops at most this long are fully
    #: unrolled on CPU backends
    SCAN_UNROLL_MAX_TICKS = 16

    def scan_unroll_for(self, mesh: Mesh) -> int:
        """Tick-loop unroll factor for a step compiled on ``mesh``.

        XLA:CPU executes a ``lax.scan``'s while-loop body through the
        sequential thunk path — convolution/matmul kernels inside it
        lose intra-op threading, which measured ~3x slower than the
        identical straight-line code (the round-5 2.1x "split overhead"
        was mostly this, taxing the M=1 unsplit baseline hardest).
        ``auto`` therefore fully unrolls the tick loop on CPU meshes
        when it is short (<= SCAN_UNROLL_MAX_TICKS ticks) and keeps the
        compact scan elsewhere: on TPU the while loop costs nothing
        and unrolling an A-branch switch per tick only bloats compile
        time.  An int ``scan_unroll`` forces the factor everywhere.
        """
        if self.scan_unroll != "auto":
            return max(1, int(self.scan_unroll))
        A = int(mesh.shape["stage"]) if "stage" in mesh.axis_names else 1
        ticks = self.num_microbatches + A - 1
        on_cpu = next(iter(mesh.devices.flat)).platform == "cpu"
        if on_cpu and ticks <= self.SCAN_UNROLL_MAX_TICKS:
            return ticks
        return 1

    def stage_param_layout(self, stage_axis_size: int) -> "StageParamLayout":
        """Memoized :class:`StageParamLayout` for an ``A``-wide stage
        axis (virtual stages: each device owns ``n_stages/A``
        consecutive stages)."""
        if stage_axis_size not in self._layout_cache:
            self._layout_cache[stage_axis_size] = StageParamLayout(
                self, stage_axis_size)
        return self._layout_cache[stage_axis_size]

    # -- wire packing ------------------------------------------------------
    # A boundary may be any pytree (e.g. BERT's (hidden, attention_mask)
    # — models/bert.py threads the pad mask with the activations): leaves
    # are flattened per sample, concatenated, and padded to the widest
    # INTERIOR boundary so every stage hop moves one (mb, max_flat)
    # buffer; the final output rides its own exact-width slot.

    def _to_wire(self, x) -> jnp.ndarray:
        leaves = jax.tree_util.tree_leaves(x)
        flat = jnp.concatenate(
            [v.reshape(v.shape[0], -1).astype(self.wire_dtype)
             for v in leaves], axis=1)
        pad = self.max_flat - flat.shape[1]
        return jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat

    def _from_wire(self, wire, struct):
        leaves, treedef = jax.tree_util.tree_flatten(struct)
        out, off = [], 0
        for leaf in leaves:
            n = _flat_size(leaf.shape)
            out.append(wire[:, off:off + n].astype(leaf.dtype).reshape(
                (wire.shape[0],) + tuple(leaf.shape[1:])))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- per-device pipeline body -----------------------------------------

    def loss_from_logits(self, logits, labels):
        if self.loss_name == "softmax_cross_entropy":
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        if self.loss_name == "mse":
            return jnp.mean((logits - labels) ** 2)
        raise ValueError(f"unknown loss {self.loss_name!r}")

    def _device_branch(self, d: int, k: int, train: bool,
                       last: bool = False, layout=None):
        """Branch for mesh-axis position ``d`` holding stages
        ``[d*k, (d+1)*k)`` chained locally (virtual pipeline stages).

        ``k == 1`` is the classic one-stage-per-device GPipe mapping; on a
        1-wide ``stage`` axis (single chip) the whole split model chains
        locally — same cut semantics and microbatch accumulation, no
        inter-device hop.  Activations between co-located stages stay in
        their native shape/dtype (no wire round-trip).

        Every branch has identical signature and output shapes
        (lax.switch requirement): ``(params, stats, wire_in, rng_data,
        labels_mb) -> (wire, slot, stats, aux)``.  Under streamed loss
        (default) ``slot`` is a scalar: the ``last`` branch fuses the
        final stage's apply WITH the microbatch's loss in one
        (optionally rematerialized) block — the logits are consumed
        where they are produced and never ride a buffer; interior
        branches return ``0.0``.  With ``stream_loss=False`` ``slot``
        is the exact-width ``(mb, n_out)`` output tail the scan
        collects into the materialized logits buffer (parity oracle).

        ``layout`` switches the parameter source: ``None`` reads the
        stage slice out of the replicated full tree; a
        :class:`StageParamLayout` unpacks it from this device's flat
        stage-sliced segment.
        """
        lo, hi = d * k, (d + 1) * k
        in_struct = self.boundary[lo]

        def stage_params_of(params, s):
            if layout is not None:
                return layout.unpack_stage(d, s, params)
            a, b = self.ranges[s]
            return shard_params(params, self.specs, a, b)

        def apply_device(params, stats, wire_in, rng_data, labels_mb):
            x = self._from_wire(wire_in, in_struct)
            new_stats = dict(stats)
            aux = jnp.zeros(())
            loss_mb = jnp.zeros(())
            for s in range(lo, hi):
                model = self.stage_models[s]
                a, b = self.ranges[s]
                fuse_loss = (self.stream_loss and last and s == hi - 1)

                # raw uint32 key data stays raw across the remat/switch
                # boundary: typed PRNG key avals confuse lax.switch's
                # residual unification under autodiff (observed MLIR
                # verifier failure, jax 0.9)
                def apply_one(sp, st_in, x, rng_data, labels,
                              model=model, a=a, b=b, fuse=fuse_loss):
                    from split_learning_tpu.parallel.expert import (
                        moe_aux_loss,
                    )
                    rng = jax.random.wrap_key_data(rng_data)
                    variables: dict = {"params": sp}
                    st = shard_params(st_in, self.specs, a, b)
                    if st:
                        variables["batch_stats"] = st
                    out, mut = model.apply(
                        variables, x, train=train,
                        mutable=["batch_stats", "intermediates"],
                        rngs={"dropout": rng} if train else None)
                    if fuse:
                        # streamed loss: reduce the final output to the
                        # microbatch loss INSIDE this block, so when the
                        # block is rematerialized no logits-sized
                        # residual survives a pipeline tick.  f32
                        # scalar: a bf16 model's loss would otherwise
                        # break lax.switch's identical-type requirement
                        # against the interior branches' f32 zeros
                        out = self.loss_from_logits(
                            jax.tree_util.tree_leaves(out)[0],
                            labels).astype(jnp.float32)
                    # sown MoE load-balance losses (zero for dense
                    # stages) join the objective on THIS device
                    return (out, mut.get("batch_stats", {}),
                            moe_aux_loss(mut.get("intermediates", {})))

                if self.stage_remat[s]:
                    apply_one = jax.checkpoint(apply_one)
                out, mut_stats, stage_aux = apply_one(
                    stage_params_of(params, s), new_stats, x, rng_data,
                    labels_mb)
                new_stats.update(mut_stats)
                aux = aux + stage_aux
                if fuse_loss:
                    loss_mb = out
                else:
                    x = out
            mb = wire_in.shape[0]
            if self.stream_loss:
                if last:
                    return (jnp.zeros((mb, self.max_flat),
                                      self.wire_dtype),
                            loss_mb, new_stats, aux)
                return (self._to_wire(x), jnp.zeros(()), new_stats, aux)
            if last:
                tail = jnp.concatenate(
                    [v.reshape(mb, -1).astype(self.wire_dtype)
                     for v in jax.tree_util.tree_leaves(x)], axis=1)
                return (jnp.zeros((mb, self.max_flat), self.wire_dtype),
                        tail, new_stats, aux)
            return (self._to_wire(x),
                    jnp.zeros((mb, self.n_out), self.wire_dtype),
                    new_stats, aux)

        return apply_device

    def device_loss(self, params, stats, x_mb, labels, rng,
                    train: bool = True,
                    mesh_axes: tuple = ("client", "stage"),
                    stage_axis_size: int | None = None,
                    layout=None, scan_unroll: int = 1):
        """Per-device pipelined loss. Must run inside shard_map with a
        ``stage`` axis of size ``stage_axis_size`` (default: one device
        per stage).  When the axis is smaller than ``n_stages`` each
        device chains ``n_stages/axis`` consecutive stages locally.

        Under streamed loss (default) the scan carry holds ONE
        accumulating loss scalar: each tick the last device folds its
        just-finished microbatch's loss in (cross-entropy computed
        inside the final stage block on that tick's logits).  The
        materialized path (``stream_loss=False``) instead collects every
        microbatch's logits into an ``(M, mb, n_out)`` buffer and runs
        one loss over the collapse — identical numerics, plus one
        logits-sized buffer per device.

        ``layout`` (a :class:`StageParamLayout`) makes ``params`` this
        device's flat stage-sliced segment instead of the replicated
        full tree (:func:`make_sliced_train_step`).

        Returns ``(local_loss, (loss, new_stats))``: ``local_loss`` is this
        device's (unsummed) contribution — the value to differentiate;
        ``loss`` is the stage-psum'd scalar for reporting, and ``new_stats``
        the stage-merged batch stats.
        """
        S, M = self.n_stages, self.num_microbatches
        A = S if stage_axis_size is None else stage_axis_size
        if S % A != 0:
            raise ValueError(
                f"n_stages={S} must be a multiple of the stage axis "
                f"size {A}")
        k = S // A
        dev = jax.lax.axis_index("stage")
        branches = [self._device_branch(d, k, train, last=(d == A - 1),
                                        layout=layout)
                    for d in range(A)]
        stats0 = stats

        def tick(carry, t):
            act_wire, stats, acc, aux_acc = carry
            inj_idx = jnp.clip(t, 0, M - 1)
            x_inj = self._to_wire(
                jax.lax.dynamic_index_in_dim(x_mb, inj_idx, 0,
                                             keepdims=False))
            act_in = jnp.where(dev == 0, x_inj, act_wire)
            mb_idx = jnp.clip(t - dev, 0, M - 1)
            rng_t = jax.random.fold_in(rng, mb_idx)
            if self.seq_axis is not None:
                # distinct dropout masks per sequence block (a shared
                # rng would repeat one block's pattern along the axis)
                rng_t = jax.random.fold_in(
                    rng_t, jax.lax.axis_index(self.seq_axis))

            # the microbatch the LAST device finishes this tick (bubble
            # ticks clip to a garbage slot that `collect` masks off)
            c_idx = jnp.clip(t - (A - 1), 0, M - 1)
            labels_t = jax.lax.dynamic_index_in_dim(labels, c_idx, 0,
                                                    keepdims=False)
            out_wire, out_slot, new_stats, aux = jax.lax.switch(
                dev, branches, params, stats, act_in,
                jax.random.key_data(rng_t), labels_t)

            # bubble ticks compute garbage: keep their stats out
            valid = (t >= dev) & (t < dev + M)
            new_stats = jax.tree_util.tree_map(
                lambda n, o: jnp.where(valid, n, o), new_stats, stats)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)

            collect = (dev == A - 1) & (t >= A - 1)
            if self.stream_loss:
                # streamed: fold the finished microbatch's loss scalar
                # (zeros on interior devices and bubble ticks)
                acc = acc + jnp.where(collect, out_slot, 0.0)
            else:
                # materialized: collect logits for microbatch t-(A-1)
                # from the exact-width tail slot
                acc = jnp.where(
                    collect,
                    jax.lax.dynamic_update_index_in_dim(
                        acc, out_slot, c_idx, 0),
                    acc)

            perm = [(i, i + 1) for i in range(A - 1)]
            act_next = (jax.lax.ppermute(out_wire, "stage", perm)
                        if perm else out_wire)
            return (act_next, new_stats, acc, aux_acc), None

        del mesh_axes  # only relevant under check_vma, which we disable
        act0 = jnp.zeros((self.mb_size, self.max_flat), self.wire_dtype)
        acc0 = (jnp.zeros(()) if self.stream_loss
                else jnp.zeros((M, self.mb_size, self.n_out),
                               self.wire_dtype))
        # full unroll must be requested as an int >= 2: both unroll=1
        # and unroll=True (which lax.scan resolves to unroll=length,
        # i.e. 1 for a single-tick loop) take the while-loop path,
        # keeping the XLA:CPU sequential-thunk tax the unroll exists
        # to remove
        ticks = M + A - 1
        unroll = (max(2, ticks) if scan_unroll >= ticks
                  else max(1, scan_unroll))
        (_, stats_f, acc, aux_acc), _ = jax.lax.scan(
            tick, (act0, stats0, acc0, jnp.zeros(())),
            jnp.arange(ticks), unroll=unroll)

        if self.stream_loss:
            # equal microbatch sizes: the mean of per-microbatch means
            # IS the flat (M*mb) mean of the materialized path
            ce_local = jnp.where(dev == A - 1, acc / M, 0.0)
        else:
            logits = acc.astype(self.out_struct.dtype).reshape(
                (M * self.mb_size,) + tuple(self.out_struct.shape[1:]))
            # collapse (M, mb, ...) -> (M*mb, ...): int labels stay 1-D
            # for CE, vector targets keep their feature dims for MSE
            labels_flat = labels.reshape((M * self.mb_size,)
                                         + labels.shape[2:])
            ce_local = jnp.where(dev == A - 1,
                                 self.loss_from_logits(logits,
                                                       labels_flat),
                                 0.0)
        # MoE load-balance aux (mean over microbatches, weighted) joins
        # the objective on whichever device computed it; dense models sow
        # nothing and aux_acc is identically 0.  Reported loss stays CE.
        local = ce_local + self.moe_aux_weight * aux_acc / M
        if self.seq_axis is not None:
            # equal static blocks: the local token-block mean / n_seq is
            # this device's share of the GLOBAL token mean; psum of the
            # shares' grads over `seq` (make_train_step) rebuilds exact
            # full-sequence gradients on the seq-replicated params
            local = local / jax.lax.axis_size(self.seq_axis)
            ce_report = ce_local / jax.lax.axis_size(self.seq_axis)
        else:
            ce_report = ce_local
        # NOTE: `local` (CE nonzero only on the last device, aux on the
        # device that owns the MoE stage) is what must be differentiated.
        # Cross-stage gradient flow happens through the ppermute
        # transpose; psum-ing the loss BEFORE grad would seed a cotangent
        # on every stage replica and overcount grads by A.
        loss = jax.lax.psum(jax.lax.stop_gradient(ce_report), "stage")
        if self.seq_axis is not None:
            loss = jax.lax.psum(loss, self.seq_axis)

        # exactly one stage updated each stats leaf; share via delta-psum
        delta = jax.tree_util.tree_map(lambda f, i: f - i, stats_f, stats0)
        if self.seq_axis is not None:
            # seq replicas each normalized their own token block: keep
            # the stage-replicated stats identical by averaging
            delta = jax.tree_util.tree_map(
                lambda d: jax.lax.pmean(d, self.seq_axis), delta)
        stats_out = jax.tree_util.tree_map(
            lambda i, d: i + jax.lax.psum(d, "stage"), stats0, delta)
        return local, (loss, stats_out)


class StageParamLayout:
    """Static flat layout of per-device stage-parameter segments.

    Device ``d`` of an ``A``-wide stage axis owns stages
    ``[d*k, (d+1)*k)``; its parameters ride as ONE flat fp32 segment —
    the raveled leaves of its stages' subtrees, concatenated
    stage-major, padded to the widest device segment — so a
    ``(client, stage)``-sharded ``(C, A*seg_len)`` array gives every
    device exactly (and only) its own slice of the model.  Compared to
    the replicated layout this cuts per-device parameter, gradient and
    optimizer-state residency by ~(A-1)/A and removes the per-step
    full-tree gradient psum over ``stage``.

    fp32 is a lossless carrier for fp32/bf16/int leaves; leaf dtypes are
    restored on unpack from the recorded shapes.
    """

    def __init__(self, pipe: "PipelineModel", stage_axis_size: int):
        S = pipe.n_stages
        if stage_axis_size <= 0 or S % stage_axis_size:
            raise ValueError(
                f"n_stages={S} must be a multiple of the stage axis "
                f"size {stage_axis_size}")
        self.pipe = pipe
        self.A = stage_axis_size
        self.k = S // stage_axis_size
        self.dtype = jnp.float32
        #: (d, s) -> (treedef, [(shape, dtype, offset, size)])
        self._meta: dict = {}
        seg_lens = []
        for d in range(self.A):
            off = 0
            for s in range(d * self.k, (d + 1) * self.k):
                a, b = pipe.ranges[s]
                sub = shard_params(pipe.param_shapes, pipe.specs, a, b)
                leaves, treedef = jax.tree_util.tree_flatten(sub)
                metas = []
                for leaf in leaves:
                    size = int(np.prod(leaf.shape))
                    metas.append((tuple(leaf.shape), leaf.dtype, off,
                                  size))
                    off += size
                self._meta[(d, s)] = (treedef, metas)
            seg_lens.append(off)
        self.seg_len = max(seg_lens) if seg_lens else 0

    def pack(self, params) -> jnp.ndarray:
        """Full layer-keyed param tree -> ``(A, seg_len)`` flat wire."""
        rows = []
        for d in range(self.A):
            parts = []
            for s in range(d * self.k, (d + 1) * self.k):
                a, b = self.pipe.ranges[s]
                sub = shard_params(params, self.pipe.specs, a, b)
                parts += [jnp.ravel(leaf).astype(self.dtype)
                          for leaf in jax.tree_util.tree_leaves(sub)]
            v = (jnp.concatenate(parts) if parts
                 else jnp.zeros((0,), self.dtype))
            rows.append(jnp.pad(v, (0, self.seg_len - v.shape[0])))
        return jnp.stack(rows)

    def unpack_stage(self, d: int, s: int, seg) -> dict:
        """Device ``d``'s flat segment -> stage ``s``'s param subtree."""
        treedef, metas = self._meta[(d, s)]
        leaves = [seg[off:off + size].reshape(shape).astype(dtype)
                  for shape, dtype, off, size in metas]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def unpack(self, wire) -> dict:
        """``(A, seg_len)`` (or flat ``(A*seg_len,)``) wire -> full
        layer-keyed tree (host-side reassembly at FedAvg / validation /
        checkpoint boundaries)."""
        wire = jnp.asarray(wire).reshape(self.A, self.seg_len)
        out: dict = {}
        for d in range(self.A):
            for s in range(d * self.k, (d + 1) * self.k):
                out.update(self.unpack_stage(d, s, wire[d]))
        return out


def _strip(tree):
    return jax.tree_util.tree_map(lambda a: a[0], tree)


def _restore(tree):
    return jax.tree_util.tree_map(lambda a: a[None], tree)



def _shmap_kwargs(mesh: Mesh) -> dict:
    """Extra ``jax.shard_map`` kwargs for this mesh.

    On a (client, stage[, seq]) mesh every axis is manual (the
    default).  When the mesh carries a ``model`` tensor-parallel or
    ``expert`` axis, that axis is left to GSPMD — parameters sharded
    under :func:`split_learning_tpu.parallel.tensor.tp_spec` /
    :func:`split_learning_tpu.parallel.expert.ep_spec` get their
    collectives (all-gather after column-parallel, psum after
    row-parallel, dispatch/combine all-to-alls around the expert FFNs)
    derived by XLA *inside* the manual pipeline body.
    """
    auto = {"model", "expert"} & set(mesh.axis_names)
    if auto:
        return {"axis_names": frozenset(set(mesh.axis_names) - auto)}
    return {}


def _make_grad_sync(client_sync: dict | None, mesh: Mesh):
    """Shared grouped-gradient-mean closure for the dense and LoRA steps.

    Returns ``sync(grads_by_layer, c_idx)`` applying the per-layer
    ``axis_index_groups`` psum-mean, or None when no sync is configured.
    """
    if not client_sync:
        return None
    n_client = mesh.shape["client"]
    group_denom = {}
    for name, groups in client_sync.items():
        sizes = np.ones(n_client, np.float32)
        for g in groups:
            for col in g:
                sizes[col] = len(g)
        group_denom[name] = sizes

    def sync(grads_part, c_idx):
        synced = dict(grads_part)
        for name, groups in client_sync.items():
            if name not in grads_part:
                continue
            denom = jnp.asarray(group_denom[name])[c_idx]
            synced[name] = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(
                    g, "client", axis_index_groups=groups) / denom,
                grads_part[name])
        return synced

    return sync


def make_train_step(pipe: PipelineModel, optimizer: optax.GradientTransformation,
                    mesh: Mesh, train: bool = True,
                    donate: bool = True,
                    client_sync: dict | None = None) -> Callable:
    """Jitted multi-client pipelined train step.

    Inputs are stacked along a leading ``client`` axis and sharded over the
    mesh's ``client`` dimension:

    * ``params``/``opt_state``/``stats``: leaves of shape (C, ...) —
      per-client model replicas (federated: NO gradient sync across
      clients; they only meet at the FedAvg barrier);
    * ``x``: (C, M, mb, ...), ``labels``: (C, M, mb);
    * ``rngs``: jax typed key array of shape (C,).

    ``client_sync`` maps a top-level param key (layer name) to
    ``axis_index_groups`` partitioning the client axis: gradients for that
    layer are mean-synced within each group every step.  This expresses
    the reference's shared later-stage clients — N stage-1 clients feeding
    one stage-2 client through a shared queue (``src/train/VGG16.py:154``)
    train that stage-2 shard on ALL their activations, which in the
    synchronous mesh regime is exactly a grouped gradient mean.  DCSL's
    server-side data aggregation (``other/DCSL/src/Scheduler.py:152-191``,
    one fwd/bwd over ``sda_size`` concatenated client batches) is the same
    mechanism with a full-axis group.

    The mesh's ``stage`` axis may be smaller than ``pipe.n_stages`` (it
    must divide it): stages are then blocked onto devices as virtual
    pipeline stages — on a 1-wide axis the whole split model runs on one
    device with microbatch gradient accumulation (no collective hops),
    preserving cut semantics on a single chip.

    Returns (params, opt_state, stats, loss[C]).
    """
    grad_sync = _make_grad_sync(client_sync, mesh)
    stage_axis = int(mesh.shape["stage"])
    unroll = pipe.scan_unroll_for(mesh)
    # seq-sharded pipelines: grads are per-stage AND per-token-block
    # partial sums; one psum over both axes restores full gradients on
    # the (stage, seq)-replicated params
    sync_axes = (("stage",) if pipe.seq_axis is None
                 else ("stage", pipe.seq_axis))

    def body(params, opt_state, stats, x, labels, rngs):
        params, opt_state, stats = map(_strip, (params, opt_state, stats))
        x, labels, rng = x[0], labels[0], rngs[0]

        def loss_fn(p):
            local, aux = pipe.device_loss(p, stats, x, labels, rng,
                                          train=train,
                                          stage_axis_size=stage_axis,
                                          scan_unroll=unroll)
            return local, aux

        (_, (loss, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        # each device produced grads for its own stage only; sync replicas
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, sync_axes), grads)
        if grad_sync is not None:
            grads = grad_sync(grads, jax.lax.axis_index("client"))
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return (*map(_restore, (new_params, new_opt, new_stats)),
                loss[None])

    spec_c = P("client")
    # x/labels carry the sequence on their last dim (token models):
    # shard it over `seq` so each device sees its block
    spec_x = (spec_c if pipe.seq_axis is None
              else P("client", None, None, pipe.seq_axis))
    # check_vma=False: jax 0.9's varying-axis tracker miscompiles the
    # transpose of the scan-of-ppermute pipeline (observed: heap corruption
    # and garbage gradients on the CPU backend). Replication along `stage`
    # is guaranteed manually by the grad/stats psums in `body`.
    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec_c, spec_c, spec_c, spec_x, spec_x, spec_c),
        out_specs=(spec_c,) * 4,
        check_vma=False,
        **_shmap_kwargs(mesh),
    )
    return jax.jit(mapped, donate_argnums=(0, 1, 2) if donate else ())


def make_sliced_train_step(pipe: PipelineModel,
                           optimizer: optax.GradientTransformation,
                           mesh: Mesh, train: bool = True,
                           donate: bool = True) -> Callable:
    """Stage-sliced parameter residency variant of :func:`make_train_step`.

    Parameters ride as the flat ``(C, A*seg_len)`` fp32 wire of
    :meth:`PipelineModel.stage_param_layout` (build with
    :func:`slice_params_for_mesh`), sharded ``(client, stage)``: each
    device holds ONLY its own stages' parameters (~1/A of the model plus
    padding) instead of a full replica.  Gradients come back for the
    local slice alone, so the per-step full-tree gradient psum over
    ``stage`` — A redundant copies of every gradient, every step —
    disappears, and optimizer state shards identically for free.

    Contract differences vs the replicated step:

    * the optimizer must be elementwise (sgd / momentum / adam / adamw
      families): it sees one flat vector, not the layer tree, so
      per-layer transforms (masking, layerwise lr) don't apply;
    * ``client_sync`` grouped gradient means are not supported (no
      per-layer gradient access) — shared-later-stage plans keep the
      replicated step;
    * the returned params are the updated flat wire; reassemble the
      full tree at round boundaries with
      ``pipe.stage_param_layout(A).unpack(wire[c])``.  FedAvg over
      clients works directly on the wire
      (``make_fedavg_step(mesh, param_spec=P("client", "stage"))``).

    Returns ``step(params_wire, opt_state, stats, x, labels, rngs) ->
    (params_wire, opt_state, stats, loss[C])``.
    """
    stage_axis = int(mesh.shape["stage"])
    layout = pipe.stage_param_layout(stage_axis)
    unroll = pipe.scan_unroll_for(mesh)

    def body(params, opt_state, stats, x, labels, rngs):
        p = params[0]                      # (seg_len,) own-stage slice
        opt_state, stats = map(_strip, (opt_state, stats))
        x, labels, rng = x[0], labels[0], rngs[0]

        def loss_fn(pv):
            local, aux = pipe.device_loss(pv, stats, x, labels, rng,
                                          train=train,
                                          stage_axis_size=stage_axis,
                                          layout=layout,
                                          scan_unroll=unroll)
            return local, aux

        (_, (loss, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p)
        # grads are purely LOCAL (this device's slice): no stage psum.
        # Seq-sharded pipelines still fold token-block partial sums.
        if pipe.seq_axis is not None:
            grads = jax.lax.psum(grads, pipe.seq_axis)
        updates, new_opt = optimizer.update(grads, opt_state, p)
        new_p = optax.apply_updates(p, updates)
        return (new_p[None], _restore(new_opt), _restore(new_stats),
                loss[None])

    # optimizer-state specs mirror the flat param wire: vector leaves
    # (moments) shard (client, stage); scalars (count) stay client-only
    opt_struct = jax.eval_shape(
        optimizer.init,
        jax.ShapeDtypeStruct((stage_axis * layout.seg_len,),
                             layout.dtype))
    spec_opt = jax.tree_util.tree_map(
        lambda leaf: (P("client", "stage") if leaf.ndim >= 1
                      else P("client")),
        opt_struct)
    spec_c = P("client")
    spec_x = (spec_c if pipe.seq_axis is None
              else P("client", None, None, pipe.seq_axis))
    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P("client", "stage"), spec_opt, spec_c, spec_x,
                  spec_x, spec_c),
        out_specs=(P("client", "stage"), spec_opt, spec_c, spec_c),
        check_vma=False,
        **_shmap_kwargs(mesh),
    )
    return jax.jit(mapped, donate_argnums=(0, 1, 2) if donate else ())


def slice_params_for_mesh(pipe: PipelineModel, params, n_clients: int,
                          mesh: Mesh):
    """Pack a full param tree into the client-stacked stage-sliced wire
    and place it: ``(C, A*seg_len)`` sharded ``(client, stage)``."""
    layout = pipe.stage_param_layout(int(mesh.shape["stage"]))
    wire = layout.pack(params).reshape(-1)
    stacked = jnp.broadcast_to(wire[None], (n_clients,) + wire.shape)
    return jax.device_put(
        stacked, NamedSharding(mesh, P("client", "stage")))


def shard_sliced_opt_to_mesh(opt_state, mesh: Mesh):
    """Place client-stacked optimizer state for the sliced step: vector
    leaves (moments over the flat wire) shard ``(client, stage)``;
    scalars (count) stay client-sharded only."""
    def put(leaf):
        spec = (P("client", "stage") if jnp.ndim(leaf) >= 2
                else P("client"))
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, opt_state)


def make_lora_train_step(pipe: PipelineModel,
                         optimizer: optax.GradientTransformation,
                         mesh: Mesh, lora_alpha: float, lora_rank: int,
                         donate: bool = True,
                         client_sync: dict | None = None) -> Callable:
    """LoRA variant of :func:`make_train_step`.

    Parameters ride as ``(frozen, trainable)`` per client —
    ``trainable = {"lora": adapters, "head": unfrozen layers}`` — and the
    pipelined loss differentiates the *merged* model w.r.t. the trainable
    tree only (peft semantics, ``src/RpcClient.py:61-66``).  Both trees
    are client-stacked so FLEX-style per-client bases keep working.

    Returns ``step(frozen_c, t_c, opt_c, stats_c, x, labels, rngs) ->
    (t_c, opt_c, stats_c, loss)``; frozen never changes.
    """
    from split_learning_tpu.ops.lora import lora_merge

    grad_sync = _make_grad_sync(client_sync, mesh)
    stage_axis = int(mesh.shape["stage"])
    unroll = pipe.scan_unroll_for(mesh)

    def body(frozen, t, opt_state, stats, x, labels, rngs):
        frozen, t, opt_state, stats = map(_strip,
                                          (frozen, t, opt_state, stats))
        x, labels, rng = x[0], labels[0], rngs[0]

        def loss_fn(tt):
            merged = lora_merge({**frozen, **tt["head"]}, tt["lora"],
                                alpha=lora_alpha, rank=lora_rank)
            local, aux = pipe.device_loss(merged, stats, x, labels, rng,
                                          train=True,
                                          stage_axis_size=stage_axis,
                                          scan_unroll=unroll)
            return local, aux

        (_, (loss, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(t)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "stage"), grads)
        if grad_sync is not None:
            c_idx = jax.lax.axis_index("client")
            grads = {"lora": grad_sync(grads["lora"], c_idx),
                     "head": grad_sync(grads["head"], c_idx)}
        updates, new_opt = optimizer.update(grads, opt_state, t)
        new_t = optax.apply_updates(t, updates)
        return (*map(_restore, (new_t, new_opt, new_stats)), loss[None])

    spec_c = P("client")
    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec_c,) * 7,
        out_specs=(spec_c,) * 4,
        check_vma=False,
    )
    # frozen (arg 0) is returned unchanged and must NOT be donated; the
    # trainable/opt/stats buffers are dead after the step and reused
    return jax.jit(mapped, donate_argnums=(1, 2, 3) if donate else ())


def make_fedavg_step(mesh: Mesh, param_spec: P | None = None) -> Callable:
    """Jitted round barrier: weighted FedAvg of per-client params over the
    ``client`` mesh axis (weights = samples consumed, the reference's
    ``data_count`` semantics at ``src/Server.py:169-179``).

    ``param_spec`` overrides the parameter placement — pass
    ``P("client", "stage")`` to average the stage-sliced flat wire of
    :func:`make_sliced_train_step` in place (the psum stays over
    ``client`` only; each device folds just its own slice)."""
    param_spec = P("client") if param_spec is None else param_spec

    def body(params, weights):
        p, w = _strip(params), weights[0]
        avg = fedavg_psum(p, w, "client")
        return _restore(avg)

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(param_spec, P("client")),
        out_specs=param_spec, check_vma=False,
        **_shmap_kwargs(mesh))
    return jax.jit(mapped)


# --------------------------------------------------------------------------
# host-side helpers
# --------------------------------------------------------------------------

def init_pipeline_variables(pipe: PipelineModel, rng,
                            example_input) -> dict:
    """Initialize FULL-model variables once on host (single device)."""
    x = jnp.zeros(example_input.shape, example_input.dtype)
    return pipe.full_model.init(rng, x, train=False)


def stack_for_clients(tree, n_clients: int):
    """Broadcast a host pytree to a leading client axis."""
    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(jnp.asarray(a)[None],
                                   (n_clients,) + jnp.asarray(a).shape),
        tree)


def shard_to_mesh(tree, mesh: Mesh):
    """Place a client-stacked pytree onto the mesh: client-sharded,
    stage-replicated — and, when the mesh carries a ``model`` or
    ``expert`` axis, tensor-/expert-sharded per leaf under the
    path-based rules of
    :func:`split_learning_tpu.parallel.tensor.tp_spec` /
    :func:`split_learning_tpu.parallel.expert.ep_spec` (the rules see
    through opt-state wrappers; non-matching leaves simply
    replicate)."""
    rule = None
    if "model" in mesh.axis_names:
        from split_learning_tpu.parallel.tensor import tp_spec
        rule = tp_spec
    elif "expert" in mesh.axis_names:
        from split_learning_tpu.parallel.expert import ep_spec
        rule = ep_spec
    if rule is not None:
        import types

        def put(path, leaf):
            # the rule sizes its spec to the UNSTACKED leaf; the client
            # axis is dim 0 here
            sub = rule(path, types.SimpleNamespace(
                ndim=jnp.ndim(leaf) - 1))
            sharding = NamedSharding(mesh, P("client", *tuple(sub)))
            return jax.device_put(leaf, sharding)

        return jax.tree_util.tree_map_with_path(put, tree)
    sharding = NamedSharding(mesh, P("client"))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), tree)
