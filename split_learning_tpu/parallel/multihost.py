"""Multi-host (DCN) initialization and mesh construction.

The reference spans machines by pointing every process at one RabbitMQ
broker (``/root/reference/README.md:144-171``); activations then cross
the data-center network per batch.  The TPU-native equivalent keeps the
per-batch hops on ICI and uses DCN only for what XLA routes across
slices: ``jax.distributed.initialize`` joins the hosts into one runtime,
and a single global mesh lays the (cluster, client, stage[, seq/model])
axes over all devices — axes that should ride ICI go innermost
(fastest-varying), the data-parallel ``client``/``cluster`` axes ride
DCN where collectives are rare (one FedAvg per round).

Single-host fallback: with no coordinator configured this is a no-op
and the mesh covers the local devices, so every entry point can call
``ensure_initialized()`` unconditionally.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class HostTopology:
    coordinator: str | None = None      # "host:port" of process 0
    num_processes: int = 1
    process_id: int = 0

    @classmethod
    def from_env(cls) -> "HostTopology":
        """SLT_COORDINATOR / SLT_NUM_PROCESSES / SLT_PROCESS_ID, falling
        back to the JAX standard variables."""
        def pick(a, b, default):
            return os.environ.get(a) or os.environ.get(b) or default
        return cls(
            coordinator=(os.environ.get("SLT_COORDINATOR")
                         or os.environ.get("JAX_COORDINATOR_ADDRESS")),
            num_processes=int(pick("SLT_NUM_PROCESSES",
                                   "JAX_NUM_PROCESSES", "1")),
            process_id=int(pick("SLT_PROCESS_ID", "JAX_PROCESS_ID",
                                "0")))


def ensure_initialized(topo: HostTopology | None = None) -> bool:
    """Join the multi-host runtime if configured; True when distributed.

    Safe to call repeatedly and on a single host (returns False, no-op).
    """
    topo = topo or HostTopology.from_env()
    if topo.coordinator is None or topo.num_processes <= 1:
        return False
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return True
    jax.distributed.initialize(
        coordinator_address=topo.coordinator,
        num_processes=topo.num_processes,
        process_id=topo.process_id)
    return True


def global_mesh(axis_sizes: dict[str, int] | None = None,
                devices=None) -> Mesh:
    """Mesh over all global devices with named axes.

    ``axis_sizes`` maps axis name -> size in declaration order; a single
    ``-1`` entry absorbs the remaining device count (like a reshape).
    Defaults to ``{"client": -1, "stage": 1}`` — pure data parallelism.
    Axis order is placement order: later axes vary fastest over the
    device list, so put the communication-heavy axis (``stage``, ``seq``,
    ``model``) LAST to keep its collectives on ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    axis_sizes = dict(axis_sizes or {"client": -1, "stage": 1})
    n = len(devices)
    known = 1
    wild = None
    for name, size in axis_sizes.items():
        if size == -1:
            if wild is not None:
                raise ValueError("only one axis may be -1")
            wild = name
        else:
            known *= size
    if wild is not None:
        if n % known:
            raise ValueError(
                f"{n} devices not divisible by fixed axes {axis_sizes}")
        axis_sizes[wild] = n // known
        known *= axis_sizes[wild]
    if known != n:
        raise ValueError(
            f"axis sizes {axis_sizes} need {known} devices, have {n}")
    shape = tuple(axis_sizes.values())
    return Mesh(np.array(devices).reshape(shape),
                tuple(axis_sizes.keys()))


def local_process_info() -> dict:
    """Process/device layout facts for logs and the planner."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
