"""Config-surface composition: intra-client TP/EP axes x client DP.

``topology.tensor_parallel`` / ``topology.expert_parallel`` turn these on
from YAML alone (VERDICT r2 item 4): the mesh becomes ``(client, model)``
or ``(client, expert)``, each logical client's replica is GSPMD-sharded
over the second axis by the per-leaf rules from
:mod:`split_learning_tpu.parallel.tensor` / ``.expert``, and XLA derives
the collectives.  Clients stay federated: the step is a ``vmap`` over the
leading client dim — no gradient mixing across clients, they only meet
at the FedAvg barrier.

The step matches ``pipeline.make_train_step``'s calling convention
(client-stacked trees, ``(C, M, mb, ...)`` batches, per-client typed
keys) so :class:`~split_learning_tpu.runtime.context.MeshContext` can
swap it in without touching the round loop.  Microbatches are consumed
by a ``lax.scan`` accumulating gradients into ONE synchronous update —
the exact semantics of the pipelined step (same per-microbatch rng
folding), so split-vs-unsplit equivalence keeps holding.
"""

from __future__ import annotations

import types
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from split_learning_tpu.parallel.expert import moe_aux_loss


def leaf_axis0_spec(shape, axis_size: int, axis: str) -> P:
    """Leaf-axis-0 partition rule shared by the ZeRO-style layouts and
    the server's cross-replica-sharded weight update
    (:class:`split_learning_tpu.runtime.aggregate.MeshFoldBackend`):
    shard dim 0 over ``axis`` when it divides evenly, replicate
    otherwise — small or ragged leaves are not worth a padded layout.
    """
    if shape and shape[0] and shape[0] % axis_size == 0:
        return P(axis)
    return P()


def stacked_shardings(tree, mesh: Mesh, spec_fn, axis: str,
                      client_axis: str = "client"):
    """NamedShardings for a CLIENT-STACKED param tree: ``spec_fn``
    (e.g. ``tensor.tp_spec`` / ``expert.ep_spec``) sees each leaf as if
    unstacked; the client axis is prepended to its spec."""

    def one(path, leaf):
        shim = types.SimpleNamespace(ndim=max(0, np.ndim(leaf) - 1))
        base = tuple(spec_fn(path, shim, axis))
        return NamedSharding(mesh, P(client_axis, *base))

    return jax.tree_util.tree_map_with_path(one, tree)


def make_axes_train_step(model, optimizer: optax.GradientTransformation,
                         mesh: Mesh, spec_fn, axis: str,
                         aux_weight: float = 0.01,
                         client_axis: str = "client",
                         donate: bool = True) -> Callable:
    """Jitted client-stacked train step with GSPMD sharding over ``axis``.

    ``step(params_c, opt_c, stats_c, x, labels, rngs) ->
    (params_c, opt_c, stats_c, loss[C])`` — x ``(C, M, mb, ...)``,
    labels ``(C, M, mb[, ...])``, rngs typed keys ``(C,)``.
    """

    def per_client(params, opt_state, stats, xc, yc, rng):
        M = xc.shape[0]

        def mb_loss(p, st, xm, ym, i):
            variables = {"params": p}
            if st:
                variables["batch_stats"] = st
            out, mut = model.apply(
                variables, xm, train=True,
                mutable=["batch_stats", "intermediates"],
                rngs={"dropout": jax.random.fold_in(rng, i)})
            ce = optax.softmax_cross_entropy_with_integer_labels(
                out.astype(jnp.float32), ym).mean()
            loss = ce + aux_weight * moe_aux_loss(
                mut.get("intermediates", {}))
            return loss, (ce, mut.get("batch_stats", {}))

        def scan_body(carry, inp):
            g_acc, ce_acc, st = carry
            xm, ym, i = inp
            (_, (ce, new_st)), g = jax.value_and_grad(
                mb_loss, has_aux=True)(params, st, xm, ym, i)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            st = jax.tree_util.tree_map(lambda _, n: n, st, new_st) \
                if st else st
            return (g_acc, ce_acc + ce, st), None

        g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
        (g, ce_sum, new_stats), _ = jax.lax.scan(
            scan_body, (g0, jnp.zeros(()), stats),
            (xc, yc, jnp.arange(M)))
        g = jax.tree_util.tree_map(lambda a: a / M, g)
        updates, new_opt = optimizer.update(g, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, new_stats, ce_sum / M

    def step(params_c, opt_c, stats_c, x, labels, rngs):
        shardings = stacked_shardings(params_c, mesh, spec_fn, axis,
                                      client_axis)
        params_c = jax.lax.with_sharding_constraint(params_c, shardings)
        data_sh = NamedSharding(mesh, P(client_axis))
        x = jax.lax.with_sharding_constraint(x, data_sh)
        new_p, new_opt, new_st, loss = jax.vmap(per_client)(
            params_c, opt_c, stats_c, x, labels, rngs)
        new_p = jax.lax.with_sharding_constraint(new_p, shardings)
        return new_p, new_opt, new_st, loss

    return jax.jit(step, donate_argnums=(0, 1, 2) if donate else ())
