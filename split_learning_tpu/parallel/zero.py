"""ZeRO-1 optimizer-state sharding + low-memory Adam moments.

The pipelined train step (:mod:`split_learning_tpu.parallel.pipeline`)
replicates parameters and optimizer state along the ``stage`` mesh axis —
correct for arbitrary heterogeneous cuts, but for a billion-parameter
model the *replicated AdamW moments* are what blow past one chip's HBM
(the reference sidesteps this by giving every torch client only its own
stage's layers, ``/root/reference/src/train/VGG16.py:24-41``; the mesh
regime must solve it with sharding instead).

Two tools, composable:

* :func:`adamw_bf16_states` — drop-in optax AdamW whose first AND second
  moments are stored bfloat16 (optax's ``mu_dtype`` only covers ``mu``).
  Halves optimizer state at negligible quality cost (moments are
  smooth EMAs; the update math still runs f32).
* :func:`make_zero1_train_step` — a variant of
  ``pipeline.make_train_step`` that keeps the moments **flattened,
  padded, and sharded across the ``stage`` axis** (ZeRO stage 1,
  Rajbhandari et al. 2019).  Each device:

  1. computes its stage's gradients exactly as the dense step does
     (scan-of-ppermute pipeline, psum over ``stage``),
  2. slices the flat gradient vector to its own moment shard,
  3. runs the elementwise AdamW update on that shard only (moments in
     bf16),
  4. all_gathers the updated parameter shards along ``stage`` to
     rebuild the replicated params for the next forward.

  Memory per device: params + grads + ``2 * bf16 * n_params / A``
  moments, vs the dense step's ``2 * f32 * n_params`` — an ``A``-way
  partition on exactly the state that is redundantly replicated.

Both paths preserve the federated semantics: state is client-stacked and
client-sharded; ZeRO partitioning happens along ``stage`` (within one
logical client's pipeline group), never across clients.

Relation to the stage-sliced step
(:func:`split_learning_tpu.parallel.pipeline.make_sliced_train_step`):
ZeRO-1 shards only the MOMENTS and keeps params + grads replicated (it
all-gathers updated shards every step, and still pays the full-tree
gradient psum over ``stage``).  The sliced step shards params, grads
AND optimizer state along ``stage`` with no per-step gather/psum of
either — strictly less traffic and residency — but requires an
elementwise optimizer and no ``client_sync`` groups; ZeRO-1 remains the
tool when those constraints don't hold.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from split_learning_tpu.parallel.pipeline import (
    PipelineModel, _make_grad_sync, _restore, _shmap_kwargs, _strip,
)


class ScaleByAdamBf16State(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates


def _adam_direction(g, mu, nu, count, b1: float, b2: float, eps: float):
    """One bf16-moment Adam step on a single array.

    ``mu``/``nu`` arrive bf16, EMAs and the bias-corrected direction are
    computed f32.  Returns ``(direction, mu32, nu32)`` — the SINGLE copy
    of the moment math shared by :func:`scale_by_adam_bf16` (pytree) and
    :func:`make_zero1_train_step` (flat shard); callers store the
    moments back as bf16.
    """
    g32 = g.astype(jnp.float32)
    mu32 = b1 * mu.astype(jnp.float32) + (1 - b1) * g32
    nu32 = b2 * nu.astype(jnp.float32) + (1 - b2) * jnp.square(g32)
    cf = count.astype(jnp.float32)
    direction = (mu32 / (1 - b1 ** cf)) / (
        jnp.sqrt(nu32 / (1 - b2 ** cf)) + eps)
    return direction, mu32, nu32


def scale_by_adam_bf16(b1: float = 0.9, b2: float = 0.999,
                       eps: float = 1e-8) -> optax.GradientTransformation:
    """Adam moment tracking with BOTH moments stored bfloat16.

    The EMAs are computed in f32 and rounded to bf16 for storage; the
    bias-corrected update is computed in f32.  ``optax.scale_by_adam``
    only exposes ``mu_dtype`` — ``nu`` (the larger numerical range of
    the two) stays f32 there, which is exactly the buffer that no
    longer fits for a 1B-parameter model on one chip.
    """

    def init_fn(params):
        zeros = lambda p: jnp.zeros_like(  # noqa: E731
            p, dtype=jnp.bfloat16)
        return ScaleByAdamBf16State(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(zeros, params),
            nu=jax.tree_util.tree_map(zeros, params))

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        stepped = jax.tree_util.tree_map(
            lambda g, m, v: _adam_direction(g, m, v, count, b1, b2, eps),
            updates, state.mu, state.nu,
            is_leaf=lambda x: isinstance(x, jnp.ndarray))
        pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda t: t[i], stepped, is_leaf=lambda x: isinstance(x, tuple))
        to_bf16 = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: a.astype(jnp.bfloat16), t)
        return pick(0), ScaleByAdamBf16State(
            count=count, mu=to_bf16(pick(1)), nu=to_bf16(pick(2)))

    return optax.GradientTransformation(init_fn, update_fn)


def adamw_bf16_states(learning_rate, b1: float = 0.9, b2: float = 0.999,
                      eps: float = 1e-8, weight_decay: float = 0.0
                      ) -> optax.GradientTransformation:
    """AdamW with bf16 moments (drop-in for ``optax.adamw``)."""
    txs = [scale_by_adam_bf16(b1, b2, eps)]
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay))
    txs.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*txs)


# --------------------------------------------------------------------------
# ZeRO-1: moments flattened + sharded along the `stage` mesh axis
# --------------------------------------------------------------------------

def _flat_geometry(params_host, stage_axis: int) -> tuple[int, int]:
    """(n_params, shard_len) with shard_len * A >= n_params (padded)."""
    n = sum(int(np.prod(leaf.shape))
            for leaf in jax.tree_util.tree_leaves(params_host))
    shard = -(-n // stage_axis)  # ceil div
    return n, shard


def init_zero1_opt_state(params_host, n_clients: int,
                         stage_axis: int) -> dict:
    """Client-stacked ZeRO-1 AdamW state for ``params_host`` (unstacked).

    ``mu``/``nu`` are bf16 vectors of shape ``(C, A * shard_len)`` —
    flattened over all parameters, zero-padded to a multiple of the
    ``stage`` axis so the mesh can shard dim 1 evenly.  The per-client
    layout is defined once in :func:`zero1_init_facade`; this is just
    its client-stacking.
    """
    one = zero1_init_facade(stage_axis).init(params_host)
    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n_clients,) + a.shape), one)


def zero1_init_facade(stage_axis: int):
    """optax-lookalike whose ``init(params)`` returns ONE client's
    ZeRO-1 AdamW state (unstacked: bf16 ``mu``/``nu`` vectors padded to
    a multiple of ``stage_axis``, scalar ``count``).

    The runtime's generic call sites build optimizer state as
    ``stack_for_clients(optimizer.init(p0), c_phys)`` — handing them
    this facade yields exactly :func:`init_zero1_opt_state`'s layout
    without special-casing (``learning.optimizer: adamw-zero1`` from
    YAML, VERDICT r3 item 3)."""
    import types

    def init(params):
        _, shard = _flat_geometry(params, stage_axis)
        padded = shard * stage_axis
        return {"mu": jnp.zeros((padded,), jnp.bfloat16),
                "nu": jnp.zeros((padded,), jnp.bfloat16),
                "count": jnp.zeros((), jnp.int32)}

    return types.SimpleNamespace(init=init)


def shard_zero1_to_mesh(opt_state: dict, mesh: Mesh) -> dict:
    """Place ZeRO-1 state: moments sharded (client, stage); count
    client-sharded, stage-replicated."""
    mom = NamedSharding(mesh, P("client", "stage"))
    rep = NamedSharding(mesh, P("client"))
    return {
        "mu": jax.device_put(opt_state["mu"], mom),
        "nu": jax.device_put(opt_state["nu"], mom),
        "count": jax.device_put(opt_state["count"], rep),
    }


def make_zero1_train_step(pipe: PipelineModel, mesh: Mesh,
                          learning_rate: float, b1: float = 0.9,
                          b2: float = 0.999, eps: float = 1e-8,
                          weight_decay: float = 0.0,
                          train: bool = True,
                          donate: bool = True,
                          client_sync: dict | None = None) -> Callable:
    """Pipelined train step with ZeRO-1 sharded bf16 AdamW moments.

    Same calling convention as ``pipeline.make_train_step`` except
    ``opt_state`` must come from :func:`init_zero1_opt_state` /
    :func:`shard_zero1_to_mesh`:

    ``step(params_c, opt_c, stats_c, x, labels, rngs) ->
    (params_c, opt_c, stats_c, loss[C])``

    ``client_sync`` applies the same per-layer grouped gradient mean as
    the dense step (shared later-stage clients), BEFORE the flat shard
    slice — the moments then track the synced gradient, keeping group
    columns bit-identical exactly as the dense path does.
    """
    stage_axis = int(mesh.shape["stage"])
    grad_sync = _make_grad_sync(client_sync, mesh)
    unroll = pipe.scan_unroll_for(mesh)

    def body(params, opt_state, stats, x, labels, rngs):
        # opt moments arrive SHARDED: local block (1, shard_len)
        mu, nu = opt_state["mu"][0], opt_state["nu"][0]
        count = opt_state["count"][0]
        params, stats = _strip(params), _strip(stats)
        x, labels, rng = x[0], labels[0], rngs[0]
        shard_len = mu.shape[0]

        def loss_fn(p):
            local, aux = pipe.device_loss(p, stats, x, labels, rng,
                                          train=train,
                                          stage_axis_size=stage_axis,
                                          scan_unroll=unroll)
            return local, aux

        (_, (loss, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "stage"), grads)
        if grad_sync is not None:
            grads = grad_sync(grads, jax.lax.axis_index("client"))

        # flatten params+grads in one canonical ravel order; slice my shard
        pflat, unravel = ravel_pytree(params)
        gflat, _ = ravel_pytree(grads)
        n = pflat.shape[0]
        dev = jax.lax.axis_index("stage")
        start = dev * shard_len
        pad = shard_len * stage_axis - n
        ppad = jnp.pad(pflat, (0, pad))
        gpad = jnp.pad(gflat, (0, pad))
        p_sh = jax.lax.dynamic_slice(ppad, (start,), (shard_len,))
        g_sh = jax.lax.dynamic_slice(gpad, (start,), (shard_len,))

        # elementwise AdamW on the shard (moments stored bf16, math f32;
        # same optax.adamw ordering as adamw_bf16_states: direction +
        # decoupled decay, then lr)
        count = count + 1
        upd, mu32, nu32 = _adam_direction(g_sh, mu, nu, count, b1, b2,
                                          eps)
        if weight_decay:
            upd = upd + weight_decay * p_sh
        new_p_sh = p_sh - learning_rate * upd

        # rebuild replicated params: all_gather shards along `stage`
        gathered = jax.lax.all_gather(new_p_sh, "stage")  # (A, shard_len)
        new_params = unravel(gathered.reshape(-1)[:n])

        new_opt = {"mu": mu32.astype(jnp.bfloat16)[None],
                   "nu": nu32.astype(jnp.bfloat16)[None],
                   "count": count[None]}
        return (_restore(new_params), new_opt, _restore(new_stats),
                loss[None])

    spec_c = P("client")
    spec_opt = {"mu": P("client", "stage"), "nu": P("client", "stage"),
                "count": P("client")}
    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec_c, spec_opt, spec_c, spec_c, spec_c, spec_c),
        out_specs=(spec_c, spec_opt, spec_c, spec_c),
        check_vma=False,
        **_shmap_kwargs(mesh),
    )
    return jax.jit(mapped, donate_argnums=(0, 1, 2) if donate else ())
