"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context support at all (max 128 tokens,
SURVEY.md §5.7); these are the TPU-native primitives that make sequence
length a mesh axis, sized so the framework scales context the way the
reference scales depth:

* :func:`ring_attention` — each device holds one sequence block of
  Q/K/V; K/V blocks rotate around the ``seq`` axis via
  ``jax.lax.ppermute`` (ICI neighbor hops, overlap-friendly) while a
  flash-style online-softmax accumulator (running max / denominator /
  weighted sum) builds exact attention without ever materializing the
  full (S, S) score matrix.  Causal masking uses global block offsets
  from ``axis_index``; with ``causal=True`` fully-masked source blocks
  still traverse the ring (the schedule is static) but contribute
  nothing.
* :func:`ulysses_attention` — ``jax.lax.all_to_all`` re-shards from
  sequence-split to head-split, runs ordinary full attention locally
  (heads are embarrassingly parallel), and re-shards back.  One
  collective each way; preferable when n_heads >= ring size and the
  full S fits per device memory.

Both are pure functions of per-device blocks, differentiable (the
ppermute/all_to_all transpose gives the reverse communication pattern
automatically), and meant to be called inside ``shard_map`` over a mesh
with a ``seq`` axis — composing with the (client, stage) pipeline mesh
by adding the axis to the mesh tuple.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _online_block(q, k, v, mask, m, lse, o, scale):
    """One flash-attention accumulation step over a K/V block.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D); mask: (Sq, Sk) or None;
    m, lse: (B, H, Sq); o: (B, Sq, H, D).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # fully-masked rows keep m = -inf; exp(-inf - -inf) would be NaN
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None], p, 0.0)
    # m finite -> exponent <= 0 (safe_m >= m); m == -inf -> exp == 0.0
    corr = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
    l_new = lse * corr + p.sum(axis=-1)
    o_new = (o * corr.transpose(0, 2, 1)[..., None]
             + jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str = "seq",
                   causal: bool = False) -> jnp.ndarray:
    """Exact blockwise attention over a ring of sequence shards.

    Per-device shapes (B, S_block, H, D); must run inside
    ``shard_map``/``pmap`` with ``axis_name`` defined.  Returns the local
    output block (B, S_block, H, D).
    """
    n = jax.lax.axis_size(axis_name)
    i = jax.lax.axis_index(axis_name)
    b, s_blk, h, d = q.shape
    scale = 1.0 / np.sqrt(d)
    q32, k32, v32 = (t.astype(jnp.float32) for t in (q, k, v))

    m = jnp.full((b, h, s_blk), -jnp.inf, jnp.float32)
    lse = jnp.zeros((b, h, s_blk), jnp.float32)
    o = jnp.zeros((b, s_blk, h, d), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(j, carry):
        m, lse, o, k_cur, v_cur = carry
        src = (i - j) % n            # ring position this K/V came from
        if causal:
            q_pos = i * s_blk + jnp.arange(s_blk)[:, None]
            k_pos = src * s_blk + jnp.arange(s_blk)[None, :]
            mask = k_pos <= q_pos
        else:
            mask = None
        m, lse, o = _online_block(q32, k_cur, v_cur, mask, m, lse, o, scale)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, lse, o, k_nxt, v_nxt

    carry = (m, lse, o, k32, v32)
    # static python loop: n is a mesh constant, keeps masks cheap
    for j in range(n):
        carry = body(j, carry)
    _, lse, o, _, _ = carry
    denom = jnp.where(lse > 0, lse, 1.0).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "seq",
                      causal: bool = False) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Trades the ring's N-1 neighbor hops for two global all-to-alls:
    re-shard (B, S/N, H, D) -> (B, S, H/N, D), run plain full attention
    over the whole sequence on the local head group, and re-shard back.
    Requires H divisible by the axis size.
    """
    n = jax.lax.axis_size(axis_name)
    b, s_blk, h, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by seq axis {n}")
    # (B, S/N, H, D) -> gather seq, scatter heads -> (B, S, H/N, D)
    qg, kg, vg = (
        jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                           tiled=True)
        for t in (q, k, v))
    s_full = s_blk * n
    scale = 1.0 / np.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qg.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s_full, s_full), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vg.astype(jnp.float32))
    # (B, S, H/N, D) -> scatter seq, gather heads -> (B, S/N, H, D)
    out = jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                             tiled=True)
    return out.astype(q.dtype)


def make_sp_train_step(model, optimizer, mesh, seq_axis: str = "seq",
                       client_axis: str = "client", donate: bool = True):
    """Sequence-parallel client-stacked train step (ring attention).

    ``topology.sequence_parallel`` routes here (VERDICT r2 item 4): the
    mesh is ``(client, seq)``, ``model`` must be built with
    ``seq_axis=seq_axis`` (its attention then calls :func:`ring_attention`
    and offsets RoPE positions by the device's global block index), and
    activations/labels are sharded on the sequence dim.  Params stay
    replicated along ``seq``; each device differentiates its local-token
    loss contribution and the ``psum`` over ``seq`` (riding the ring's
    ppermute transpose) rebuilds exact full-sequence gradients.

    Same calling convention as ``pipeline.make_train_step``:
    ``step(params_c, opt_c, stats_c, x, labels, rngs)`` with
    x ``(C, M, mb, S)``, labels ``(C, M, mb, S)``, S divisible by the
    seq axis size.  Microbatch gradients accumulate into one update.
    """
    import optax
    from jax.sharding import PartitionSpec as P

    def body(params, opt_state, stats, x, labels, rngs):
        strip = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: a[0], t)
        params, opt_state, stats = map(strip, (params, opt_state, stats))
        x, labels, rng = x[0], labels[0], rngs[0]
        M = x.shape[0]
        n = jax.lax.axis_size(seq_axis)

        def mb_loss(p, xm, ym, i):
            out = model.apply({"params": p}, xm, train=True,
                              rngs={"dropout": jax.random.fold_in(rng, i)})
            ce_local = optax.softmax_cross_entropy_with_integer_labels(
                out.astype(jnp.float32), ym).mean()
            # local token-block mean / n == this device's share of the
            # global token mean (equal static blocks)
            return ce_local / n

        def scan_body(carry, inp):
            g_acc, ce_acc = carry
            xm, ym, i = inp
            ce_share, g = jax.value_and_grad(mb_loss)(params, xm, ym, i)
            return (jax.tree_util.tree_map(jnp.add, g_acc, g),
                    ce_acc + ce_share), None

        g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
        (g, ce_sum), _ = jax.lax.scan(scan_body, (g0, jnp.zeros(())),
                                      (x, labels, jnp.arange(M)))
        g = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, seq_axis) / M, g)
        loss = jax.lax.psum(ce_sum, seq_axis) / M
        updates, new_opt = optimizer.update(g, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        restore = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: a[None], t)
        return (restore(new_params), restore(new_opt), restore(stats),
                loss[None])

    spec_c = P(client_axis)
    spec_x = P(client_axis, None, None, seq_axis)
    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec_c, spec_c, spec_c, spec_x, spec_x, spec_c),
        out_specs=(spec_c,) * 4,
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(0, 1, 2) if donate else ())


def make_ring_attention_fn(mesh, axis_name: str = "seq",
                           causal: bool = False, impl: str = "ring"):
    """shard_map-wrapped callable over full (B, S, H, D) arrays sharded
    along ``axis_name`` on dim 1."""
    from jax.sharding import PartitionSpec as P

    if impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown impl {impl!r}; use ring|ulysses")
    fn = ring_attention if impl == "ring" else ulysses_attention
    spec = P(None, axis_name)

    def local(q, k, v):
        return fn(q, k, v, axis_name=axis_name, causal=causal)

    mapped = jax.shard_map(local, mesh=mesh,
                           in_specs=(spec, spec, spec), out_specs=spec,
                           check_vma=False)
    return jax.jit(mapped)
