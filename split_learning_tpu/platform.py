"""Honor ``JAX_PLATFORMS`` even when jax was pre-imported.

A ``sitecustomize`` (or any other early import) can initialize jax before
this package's CLI entry points run, at which point the ``JAX_PLATFORMS``
environment variable no longer has any effect — a child process spawned
with ``JAX_PLATFORMS=cpu`` silently lands on the site-pinned accelerator
instead.  ``jax.config.update`` wins over a pre-import, so every CLI main
calls this first.
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import sys

    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception as e:
        print(f"warning: JAX_PLATFORMS={plat} could not be applied "
              f"({e}); backends may already be initialized",
              file=sys.stderr)
        return
    try:
        got = jax.default_backend()
        if got not in plat.split(","):
            print(f"warning: JAX_PLATFORMS={plat} requested but the "
                  f"effective backend is {got!r}", file=sys.stderr)
    except Exception:
        pass  # backend init deferred — the update took effect
