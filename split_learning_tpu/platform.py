"""Honor ``JAX_PLATFORMS`` even when jax was pre-imported.

A ``sitecustomize`` (or any other early import) can initialize jax before
this package's CLI entry points run, at which point the ``JAX_PLATFORMS``
environment variable no longer has any effect — a child process spawned
with ``JAX_PLATFORMS=cpu`` silently lands on the site-pinned accelerator
instead.  ``jax.config.update`` wins over a pre-import, so every CLI main
calls this first.
"""

from __future__ import annotations

import os


def apply_compile_cache(cache_dir) -> None:
    """Point jax's persistent compilation cache at ``cache_dir``
    (config ``compile-cache-dir``; None/empty = off).

    The multi-process protocol deployment pays a cold-round compile tax
    in EVERY client/server process on EVERY restart (BENCH_r05: 38 s
    cold round vs 18 s steady); with the cache populated, a restarted
    process loads the compiled executables instead.  The threshold is
    dropped to 0 s because protocol shards compile as many small
    programs, each individually under jax's 1 s default."""
    if not cache_dir:
        return
    import sys

    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    except Exception as e:  # noqa: BLE001 — cache is an optimization;
        # a jax version without the knob must not kill the entry point
        print(f"warning: compile cache {cache_dir!r} not applied ({e})",
              file=sys.stderr)
        return
    try:
        # cache everything, including tiny executables (knob name has
        # moved across jax versions; best-effort)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
    except Exception:
        pass


def apply_platform_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import sys

    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception as e:
        print(f"warning: JAX_PLATFORMS={plat} could not be applied "
              f"({e}); backends may already be initialized",
              file=sys.stderr)
        return
    try:
        got = jax.default_backend()
        if got not in plat.split(","):
            print(f"warning: JAX_PLATFORMS={plat} requested but the "
                  f"effective backend is {got!r}", file=sys.stderr)
    except Exception:
        pass  # backend init deferred — the update took effect
