"""Client clustering by label distribution.

Parity with ``/root/reference/src/Cluster.py:5-21``: L1-normalize each
client's per-label sample-count vector, KMeans with a fixed seed, return the
per-client cluster labels and per-cluster sizes.  KMeans is implemented here
directly (kmeans++ init + Lloyd iterations, numpy) — deterministic given the
seed, no sklearn.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def _kmeans_pp_init(x: np.ndarray, k: int, rng: np.random.Generator) -> np.ndarray:
    n = x.shape[0]
    centers = np.empty((k, x.shape[1]), dtype=float)
    centers[0] = x[rng.integers(n)]
    d2 = ((x - centers[0]) ** 2).sum(axis=1)
    for i in range(1, k):
        total = d2.sum()
        if total <= 0:
            centers[i] = x[rng.integers(n)]
        else:
            centers[i] = x[rng.choice(n, p=d2 / total)]
        d2 = np.minimum(d2, ((x - centers[i]) ** 2).sum(axis=1))
    return centers


def kmeans(x: np.ndarray, k: int, n_init: int = 10, n_iter: int = 300,
           seed: int = 42) -> tuple[np.ndarray, np.ndarray]:
    """Lloyd's KMeans with kmeans++ restarts. Returns (labels, centers)."""
    rng = np.random.default_rng(seed)
    best_inertia = np.inf
    best: tuple[np.ndarray, np.ndarray] | None = None
    for _ in range(n_init):
        centers = _kmeans_pp_init(x, k, rng)
        for _ in range(n_iter):
            d2 = ((x[:, None, :] - centers[None, :, :]) ** 2).sum(axis=2)
            labels = d2.argmin(axis=1)
            new_centers = centers.copy()
            for j in range(k):
                mask = labels == j
                if mask.any():
                    new_centers[j] = x[mask].mean(axis=0)
            if np.allclose(new_centers, centers):
                centers = new_centers
                break
            centers = new_centers
        # final assignment against the *final* centers, so the returned
        # (labels, centers) pair is consistent and restarts rank correctly
        d2 = ((x[:, None, :] - centers[None, :, :]) ** 2).sum(axis=2)
        labels = d2.argmin(axis=1)
        inertia = float(d2[np.arange(x.shape[0]), labels].sum())
        if inertia < best_inertia:
            best_inertia = inertia
            best = (labels.copy(), centers.copy())
    assert best is not None
    return best


def kmeans_cluster(label_counts: Sequence[Sequence[float]], num_cluster: int,
                   seed: int = 42) -> tuple[np.ndarray, list[list[int]]]:
    """Cluster clients by L1-normalized label distribution.

    Returns ``(labels, infor_cluster)`` where ``infor_cluster[c] == [size_c]``
    — the same nested-singleton shape the reference server consumes when
    building per-cluster client counts.
    """
    x = np.asarray(label_counts, dtype=float)
    norms = np.abs(x).sum(axis=1, keepdims=True)
    norms[norms == 0] = 1.0
    x = x / norms
    k = min(num_cluster, x.shape[0])
    labels, _ = kmeans(x, k, seed=seed)
    counts = np.bincount(labels, minlength=k)
    return labels, [[int(c)] for c in counts]


def clustering_algorithm(label_counts: Sequence[Sequence[float]],
                         num_cluster: int, algorithm: str = "KMeans",
                         seed: int = 42) -> tuple[np.ndarray, list[list[int]]]:
    """Dispatch by algorithm name (config key ``algorithm-cluster``)."""
    if algorithm.lower() in ("kmeans", "k_means", "k-means"):
        return kmeans_cluster(label_counts, num_cluster, seed=seed)
    if algorithm.lower() in ("affinitypropagation", "affinity-propagation"):
        labels = affinity_propagation(np.asarray(label_counts, dtype=float))
        k = int(labels.max()) + 1 if labels.size else 0
        counts = np.bincount(labels, minlength=k)
        return labels, [[int(c)] for c in counts]
    raise ValueError(f"unknown clustering algorithm: {algorithm!r}")


def affinity_propagation(x: np.ndarray, damping: float = 0.7,
                         n_iter: int = 200, conv_iter: int = 15) -> np.ndarray:
    """Affinity propagation on negative-squared-euclidean similarity.

    Needed by BASELINE.json config #2 ("AffinityPropagation cluster mode") —
    the reference only names KMeans, so this is a fresh implementation of the
    standard responsibility/availability message passing.
    """
    norms = np.abs(x).sum(axis=1, keepdims=True)
    norms[norms == 0] = 1.0
    x = x / norms
    n = x.shape[0]
    if n == 1:
        return np.zeros(1, dtype=int)
    s = -((x[:, None, :] - x[None, :, :]) ** 2).sum(axis=2)
    pref = np.median(s[~np.eye(n, dtype=bool)])
    np.fill_diagonal(s, pref)
    # deterministic tie-breaking jitter: exact-duplicate points otherwise
    # collapse the message passing into oscillation / one cluster
    scale = max(np.abs(s).max(), 1.0)
    s = s + np.random.default_rng(0).normal(0, 1e-9 * scale, s.shape)
    r = np.zeros((n, n))
    a = np.zeros((n, n))
    stable = 0
    prev_ex = None
    for _ in range(n_iter):
        # responsibilities
        as_ = a + s
        idx = as_.argmax(axis=1)
        first = as_[np.arange(n), idx]
        as_[np.arange(n), idx] = -np.inf
        second = as_.max(axis=1)
        rnew = s - first[:, None]
        rnew[np.arange(n), idx] = s[np.arange(n), idx] - second
        r = damping * r + (1 - damping) * rnew
        # availabilities
        rp = np.maximum(r, 0)
        np.fill_diagonal(rp, r.diagonal())
        anew = rp.sum(axis=0)[None, :] - rp
        dA = anew.diagonal().copy()
        anew = np.minimum(anew, 0)
        np.fill_diagonal(anew, dA)
        a = damping * a + (1 - damping) * anew
        ex = np.flatnonzero((a + r).diagonal() > 0)
        if prev_ex is not None and ex.size and np.array_equal(ex, prev_ex):
            stable += 1
            if stable >= conv_iter:
                break
        else:
            stable = 0
        prev_ex = ex
    exemplars = np.flatnonzero((a + r).diagonal() > 0)
    if exemplars.size == 0:
        exemplars = np.array([int((a + r).diagonal().argmax())])
    labels_raw = s[:, exemplars].argmax(axis=1)
    labels_raw[exemplars] = np.arange(exemplars.size)
    return labels_raw.astype(int)
