"""Straggler rejection via a 2-component Gaussian-mixture speed threshold.

Behavioral parity with ``/root/reference/src/Selection.py:4-48``: fit a
2-component GMM to log(speed), then place the threshold at the intersection
of the two Gaussians between their means (the Bayes decision boundary);
devices slower than the threshold are rejected.  The reference leans on
sklearn — here the EM fit is a ~40-line numpy routine (1-D, full covariance)
so the planner has zero dependencies beyond numpy.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def _gmm_em_1d(x: np.ndarray, n_components: int = 2, n_init: int = 9,
               n_iter: int = 200, tol: float = 1e-7, seed: int = 0):
    """EM for a 1-D Gaussian mixture. Returns (means, variances, weights)."""
    rng = np.random.default_rng(seed)
    n = x.shape[0]
    best = None
    best_ll = -np.inf
    for _ in range(n_init):
        # init means from random data points, shared variance
        mu = rng.choice(x, size=n_components, replace=n < n_components)
        var = np.full(n_components, max(x.var(), 1e-12))
        w = np.full(n_components, 1.0 / n_components)
        ll_prev = -np.inf
        for _ in range(n_iter):
            # E-step: responsibilities (log-space for stability)
            log_p = (-0.5 * (x[:, None] - mu[None, :]) ** 2 / var[None, :]
                     - 0.5 * np.log(2 * np.pi * var[None, :])
                     + np.log(w[None, :]))
            log_norm = np.logaddexp.reduce(log_p, axis=1)
            resp = np.exp(log_p - log_norm[:, None])
            ll = float(log_norm.sum())
            # M-step
            nk = resp.sum(axis=0) + 1e-12
            mu = (resp * x[:, None]).sum(axis=0) / nk
            var = (resp * (x[:, None] - mu[None, :]) ** 2).sum(axis=0) / nk
            var = np.maximum(var, 1e-12)
            w = nk / n
            if abs(ll - ll_prev) < tol:
                break
            ll_prev = ll
        if ll > best_ll:
            best_ll = ll
            best = (mu.copy(), var.copy(), w.copy())
    return best


def auto_threshold(performance: Sequence[float], n_init: int = 9,
                   seed: int = 0) -> float:
    """Speed threshold separating the slow and fast device populations.

    Solves the quadratic for the intersection of the two fitted Gaussians in
    log-speed space; falls back to the midpoint of the means when the
    intersection is degenerate or lies outside (mu_slow, mu_fast) — the same
    decision ladder as the reference.
    """
    perf = np.asarray(performance, dtype=float)
    if perf.size <= 1:
        return 0.0
    # a dead/timed-out device may report speed <= 0; log() would poison the
    # EM likelihood and fail every restart, so clamp to a tiny positive speed
    # (such a device always lands far below any sane threshold anyway)
    perf = np.maximum(perf, 1e-300)

    x = np.log(perf)
    mu_raw, var_raw, w_raw = _gmm_em_1d(x, 2, n_init=n_init, seed=seed)
    order = np.argsort(mu_raw)
    mu, var, w = mu_raw[order], var_raw[order], w_raw[order]

    # intersection of w0*N(mu0,var0) and w1*N(mu1,var1): quadratic in t
    a = var[0] - var[1]
    b = 2.0 * (var[1] * mu[0] - var[0] * mu[1])
    c = (var[0] * mu[1] ** 2 - var[1] * mu[0] ** 2
         + 2.0 * var[0] * var[1] * np.log((var[1] * w[0]) / (var[0] * w[1])))

    if np.isclose(a, 0.0):
        if np.isclose(b, 0.0):
            t = float(np.mean(mu))
        else:
            root = -c / b
            t = float(root) if mu[0] < root < mu[1] else float(np.mean(mu))
    else:
        roots = np.roots([a, b, c])
        real = roots[np.isreal(roots)].real
        inside = real[(real > mu[0]) & (real < mu[1])]
        if inside.size:
            mid = float(np.mean(mu))
            t = float(inside[np.argmin(np.abs(inside - mid))])
        else:
            t = float(np.mean(mu))
    return float(np.exp(t))


def select_devices(speeds: Sequence[float], enabled: bool = True,
                   n_init: int = 9, seed: int = 0) -> tuple[np.ndarray, float]:
    """Boolean keep-mask over devices plus the threshold used.

    With selection disabled, or a single device (no mixture to fit),
    everything is kept.
    """
    speeds = np.asarray(speeds, dtype=float)
    if not enabled or speeds.size <= 1:
        return np.ones(speeds.shape, dtype=bool), 0.0
    thr = auto_threshold(speeds, n_init=n_init, seed=seed)
    return speeds >= thr, thr
