"""Resource-aware planning: who runs which stage, where to cut, who is too slow.

This package is the TPU-native counterpart of the reference's server-side
"brains" (``src/Partition.py``, ``src/Selection.py``, ``src/Cluster.py`` and
the label-distribution synthesis in ``src/Server.py:87-101``).  All functions
are pure and CPU-cheap; their output feeds the mesh planner
(:mod:`split_learning_tpu.planner.mesh`) which maps (cluster, client, stage)
onto a ``jax.sharding.Mesh``.
"""

from split_learning_tpu.planner.partition import partition, partition_multiway
from split_learning_tpu.planner.selection import auto_threshold, select_devices
from split_learning_tpu.planner.cluster import kmeans_cluster, clustering_algorithm
from split_learning_tpu.planner.distribution import synthesize_label_counts
from split_learning_tpu.planner.throughput import (
    implied_bandwidth, predict_round_wall, replan_cuts, scaled_exe_time,
)

__all__ = [
    "partition",
    "partition_multiway",
    "auto_threshold",
    "select_devices",
    "kmeans_cluster",
    "clustering_algorithm",
    "synthesize_label_counts",
    "scaled_exe_time",
    "implied_bandwidth",
    "predict_round_wall",
    "replan_cuts",
]
