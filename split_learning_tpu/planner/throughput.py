"""Measured-throughput cost model for cut re-planning.

The static planner (:mod:`split_learning_tpu.planner.partition`) picks
cuts from the profiles clients registered with — a one-shot snapshot
of each device, taken before any real round ran.  The closed-loop
scheduler (``runtime/scheduler.py``) needs the same max-min
pipeline-balance search driven by LIVE telemetry instead: measured
per-client device rate (the perf plane's ``compute_samples_per_s``
gauge) and measured end-to-end rate (the telemetry plane's EWMA
``samples_per_s``), folded back onto the profile's per-layer shape and
boundary byte sizes.  This module is that bridge: pure numpy functions
that rescale profiles to measurements, invert the rate gap into an
implied wire bandwidth, predict the round wall for any cut, and search
for a better one under a damping threshold — the same shape as the
measured-profile partitioning in MPMD pipeline planning
(PAPERS.md, arxiv 2412.14374), fed by fleet telemetry rather than a
static profiling pass.
"""

from __future__ import annotations

import itertools
from typing import Sequence

import numpy as np

from split_learning_tpu.planner.partition import _group_rate


def scaled_exe_time(profile_exe: Sequence[float],
                    compute_rate: float | None) -> list[float]:
    """Per-layer execution times rescaled so their SUM matches the
    measured per-sample device time ``1 / compute_rate``.

    The profile supplies the per-layer *shape* (which layers are
    expensive relative to each other — stable across load), the
    measurement supplies the absolute speed (which drifts with
    thermal state, co-tenants, batch size).  Without a usable
    measurement the profile passes through unchanged; without a usable
    profile the measured time spreads uniformly."""
    exe = [float(t) for t in profile_exe]
    if not compute_rate or compute_rate <= 0:
        return exe
    target = 1.0 / float(compute_rate)
    total = sum(exe)
    if total <= 0:
        n = max(len(exe), 1)
        return [target / n] * n
    return [t * target / total for t in exe]


def implied_bandwidth(cut_bytes: float, rate: float | None,
                      compute_rate: float | None) -> float:
    """Bytes/s implied by the gap between a client's end-to-end rate
    and its device rate at the current cut.

    Per sample the client spends ``1/compute_rate`` on device and
    ``1/rate`` overall; the residual is wire + queueing, attributed to
    shipping ``cut_bytes`` per sample.  Returns 0.0 (the planner's
    "unconstrained" sentinel) when the gap is unmeasurable or
    non-positive — a client whose end-to-end rate matches its device
    rate is not wire-bound."""
    if not rate or not compute_rate or rate <= 0 or compute_rate <= 0:
        return 0.0
    wire_t = 1.0 / rate - 1.0 / compute_rate
    if wire_t <= 0 or cut_bytes <= 0:
        return 0.0
    return float(cut_bytes) / wire_t


def stage_rates(exe_time_groups: Sequence[Sequence[Sequence[float]]],
                net_groups: Sequence[Sequence[float]],
                cuts: Sequence[int],
                size_data: Sequence[float]) -> list[float]:
    """Aggregate throughput (samples/s) of each stage group under
    ``cuts`` — the reference's harmonic per-device rate model
    (:func:`~split_learning_tpu.planner.partition._group_rate`), with
    each group paying its incoming AND outgoing boundary transfer."""
    n_groups = len(exe_time_groups)
    bounds = (-1,) + tuple(int(c) - 1 for c in cuts) \
        + (len(size_data) - 1,)
    rates = []
    for k in range(n_groups):
        lo, hi = bounds[k] + 1, bounds[k + 1] + 1
        edge = 0.0
        if k > 0:
            edge += float(size_data[bounds[k]])
        if k < n_groups - 1:
            edge += float(size_data[bounds[k + 1]])
        rates.append(_group_rate(exe_time_groups[k], net_groups[k],
                                 slice(lo, hi), edge))
    return rates


def predict_round_wall(exe_time_groups, net_groups, cuts, size_data,
                       samples: float = 1.0) -> float:
    """Predicted round wall: the per-round sample budget divided by
    the SLOWEST stage group's aggregate rate (the pipeline's
    steady-state bottleneck).  ``inf`` when any group has no
    throughput at all (empty/unmeasured)."""
    rates = stage_rates(exe_time_groups, net_groups, cuts, size_data)
    slowest = min(rates) if rates else 0.0
    if slowest <= 0:
        return float("inf")
    return float(samples) / slowest


def replan_cuts(exe_time_groups, net_groups, size_data,
                current_cuts: Sequence[int],
                damping: float = 0.15,
                samples: float = 1.0,
                window: int = 16) -> dict:
    """Max-min cut search over the MEASURED inputs, gated by a damping
    threshold so the plan cannot flap on noise.

    Returns ``{cuts, adopted, predicted_wall_s, incumbent_wall_s,
    improvement}`` where ``adopted`` is True only when the best cut's
    predicted wall beats the incumbent's by at least ``damping``
    (fractional).  Candidates are restricted to ``window`` layers
    around each INCUMBENT cut: this runs on the protocol thread at
    every round boundary, and the scheduler's job is tracking drift —
    a deep-model full C(n_layers, k) sweep (~156k combos at 100
    layers x 4 stages) belongs to the static planner's one-shot pass,
    not the per-boundary loop.  The window covers the whole space
    whenever ``n_layers <= 2*window`` (every bench/test geometry)."""
    n_groups = len(exe_time_groups)
    n_layers = len(size_data)
    cur = [int(c) for c in current_cuts]
    incumbent = predict_round_wall(exe_time_groups, net_groups, cur,
                                   size_data, samples)
    best_cuts, best_wall = cur, incumbent
    if n_groups >= 2:
        k = n_groups - 1
        anchors = (cur if len(cur) == k
                   else [max(1, (i + 1) * (n_layers - 1) // n_groups)
                         for i in range(k)])
        cand = [range(max(1, a - window),
                      min(n_layers - 1, a + window) + 1)
                for a in anchors]
        for combo in itertools.product(*cand):
            if any(combo[i] >= combo[i + 1] for i in range(k - 1)):
                continue
            wall = predict_round_wall(exe_time_groups, net_groups,
                                      combo, size_data, samples)
            if wall < best_wall:
                best_wall = wall
                best_cuts = list(combo)
    improvement = (0.0 if not np.isfinite(incumbent) or incumbent <= 0
                   else max(0.0, 1.0 - best_wall / incumbent))
    # an unmeasurable incumbent (inf) adopts any finite plan — there
    # is nothing to damp against
    adopted = (best_cuts != cur
               and ((not np.isfinite(incumbent)
                     and np.isfinite(best_wall))
                    or improvement >= damping))
    return {
        "cuts": best_cuts if adopted else cur,
        "adopted": adopted,
        "predicted_wall_s": (round(best_wall, 6)
                             if np.isfinite(best_wall) else None),
        "incumbent_wall_s": (round(incumbent, 6)
                             if np.isfinite(incumbent) else None),
        "improvement": round(improvement, 4),
    }
