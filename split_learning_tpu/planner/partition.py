"""Throughput-optimal cut-point search.

Behavioral parity with the reference's max-min pipeline-balance search
(``/root/reference/src/Partition.py:2-21``): given per-device per-layer
execution times and network bandwidths for the two stage groups, pick the cut
that maximizes the slower group's aggregate rate.  Extended here with a
multi-way generalization (the reference only supports one cut; BASELINE.json
config #3/#5 need 3- and 4-stage splits).
"""

from __future__ import annotations

import itertools
from typing import Sequence

import numpy as np


def _group_rate(exe_times: Sequence[Sequence[float]],
                bandwidths: Sequence[float],
                compute_slice: slice,
                transfer_bytes: float) -> float:
    """Aggregate throughput of one device group.

    Each device contributes ``1 / (compute_time + transfer_bytes/bandwidth)``
    — the harmonic form the reference uses, so clients' rates add.
    """
    rate = 0.0
    for exe, bw in zip(exe_times, bandwidths):
        t = float(np.sum(np.asarray(exe, dtype=float)[compute_slice]))
        if bw > 0:       # unmeasured bandwidth (0) -> free transfer
            t += transfer_bytes / bw
        if t > 0:
            rate += 1.0 / t
    return rate


def partition(exe_time_group_1: Sequence[Sequence[float]],
              net_group_1: Sequence[float],
              exe_time_group_2: Sequence[Sequence[float]],
              net_group_2: Sequence[float],
              size_data: Sequence[float]) -> list[int]:
    """Choose the single cut maximizing ``min(rate_group1, rate_group2)``.

    ``size_data[c]`` is the byte size of the activation leaving layer ``c``
    (0-indexed).  Group 1 computes layers ``0..c`` and ships the activation;
    group 2 receives it and computes layers ``c+1..``.  Returns the 1-indexed
    cut layer in a list (matching the reference's return shape, which feeds
    straight into the per-cluster ``layers`` ranges).
    """
    best_rate = 0.0
    best_cut = 1
    n_layers = len(size_data)
    # proper cuts only: cutting after the last layer would leave group 2
    # with no compute (cheap-transfer profiles would otherwise pick it)
    for cut in range(n_layers - 1):
        size = float(size_data[cut])
        r1 = _group_rate(exe_time_group_1, net_group_1, slice(0, cut + 1), size)
        r2 = _group_rate(exe_time_group_2, net_group_2, slice(cut + 1, None), size)
        rate = min(r1, r2)
        if rate > best_rate:
            best_rate = rate
            best_cut = cut + 1
    return [best_cut]


def partition_multiway(exe_time_groups: Sequence[Sequence[Sequence[float]]],
                       net_groups: Sequence[Sequence[float]],
                       size_data: Sequence[float]) -> list[int]:
    """K-way generalization: find cuts ``c_1 < ... < c_{K-1}`` maximizing the
    minimum group rate over K stage groups.

    Group ``k`` computes layers ``c_k+1..c_{k+1}`` (with ``c_0 = -1``,
    ``c_K = n_layers-1``) and pays the transfer of *both* its boundary
    activations — incoming and outgoing (the first group has no incoming
    edge, the last no outgoing).  With K=2 this reduces exactly to the
    reference's 2-way model where each side pays the cut's transfer once.
    Exhaustive search — layer counts here are <100 and K <= 4, so the loop
    is cheap; a DP refinement can replace it if profiles ever get large.
    """
    n_groups = len(exe_time_groups)
    n_layers = len(size_data)
    if n_groups < 2:
        return []
    best_rate = -1.0
    best_cuts: tuple[int, ...] = tuple(range(1, n_groups))
    for cuts in itertools.combinations(range(n_layers - 1), n_groups - 1):
        bounds = (-1,) + cuts + (n_layers - 1,)
        worst = np.inf
        for k in range(n_groups):
            lo, hi = bounds[k] + 1, bounds[k + 1] + 1
            edge_bytes = 0.0
            if k > 0:
                edge_bytes += float(size_data[cuts[k - 1]])  # incoming
            if k < n_groups - 1:
                edge_bytes += float(size_data[cuts[k]])      # outgoing
            rate = _group_rate(exe_time_groups[k], net_groups[k],
                               slice(lo, hi), edge_bytes)
            worst = min(worst, rate)
        if worst > best_rate:
            best_rate = worst
            best_cuts = tuple(c + 1 for c in cuts)
    return list(best_cuts)
