"""Per-client label-count synthesis (IID / Dirichlet non-IID).

Parity with the reference server's ``distribution()``
(``/root/reference/src/Server.py:87-101``): stage-1 clients each get a
per-label sample-count vector — IID mode splits ``num_sample`` evenly over
labels for every client; non-IID mode draws each client's label distribution
from ``Dirichlet(alpha * 1)`` and scales to ``num_sample``.
"""

from __future__ import annotations

import numpy as np


def synthesize_label_counts(num_clients: int, num_labels: int,
                            num_samples: int, non_iid: bool = False,
                            alpha: float = 1.0,
                            seed: int | None = None) -> np.ndarray:
    """(num_clients, num_labels) int array of per-label sample counts."""
    if num_clients <= 0:
        return np.zeros((0, num_labels), dtype=int)
    if non_iid:
        rng = np.random.default_rng(seed)
        probs = rng.dirichlet([alpha] * num_labels, size=num_clients)
        counts = _largest_remainder(probs * num_samples)
    else:
        counts = _largest_remainder(
            np.full((num_clients, num_labels),
                    num_samples / num_labels, dtype=float))
    return counts


def _largest_remainder(target: np.ndarray) -> np.ndarray:
    """Round rows to ints preserving each row's total (the reference's
    plain ``int()`` truncation loses up to num_labels-1 samples per client
    and zeroes everything when num_samples < num_labels)."""
    floor = np.floor(target).astype(int)
    remainder = target - floor
    deficit = np.round(target.sum(axis=1)).astype(int) - floor.sum(axis=1)
    for i in range(target.shape[0]):
        if deficit[i] > 0:
            top = np.argsort(-remainder[i])[:deficit[i]]
            floor[i, top] += 1
    return floor


def fixed_matrix_label_counts(matrix) -> np.ndarray:
    """Pass-through for the FLEX variant's hardcoded non-IID matrix
    (``other/FLEX/src/Server.py:80-93``) expressed as a config value."""
    return np.asarray(matrix, dtype=int)
