"""``python -m split_learning_tpu.broker`` — standalone message broker.

The reference requires an external RabbitMQ (Erlang) broker
(``/root/reference/README.md:43-69``); this hosts the framework's own
TCP broker instead.  Prefers the native C++ broker when it can be built
(``split_learning_tpu/native``), falling back to the threaded Python one.
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(description="Split-learning TCP broker.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=5672)
    ap.add_argument("--python", action="store_true",
                    help="force the pure-Python broker")
    ap.add_argument("--max-frame-gb", type=float, default=None,
                    help="per-frame payload cap (default 8 GiB): a "
                         "corrupt length prefix fails the connection "
                         "instead of driving a huge allocation.  "
                         "Enforced by the pure-Python broker only "
                         "(implies --python); publishers fail-fast "
                         "against their own process's cap, so lower "
                         "it on both sides or oversized publishes "
                         "die at the broker instead of the client")
    args = ap.parse_args(argv)

    if args.max_frame_gb is not None:
        from split_learning_tpu.runtime import bus, protocol
        bus.MAX_FRAME_BYTES = int(args.max_frame_gb * (1 << 30))
        # the chunked twin lives at the ENDPOINTS: reassembly happens
        # in each server/client/aggregator process's FrameAssembler,
        # which this process cannot reach — set SLT_MAX_ASSEMBLED_GB
        # in those processes' environments to lower it there (counted
        # oversize_frames).  Lowered here too for a broker-hosted
        # server (--broker in the server process).
        protocol.MAX_ASSEMBLED_BYTES = bus.MAX_FRAME_BYTES
        if not args.python:
            print("--max-frame-gb: native broker does not enforce the "
                  "cap; using the Python broker")
            args.python = True

    broker = None
    if not args.python:
        try:
            from split_learning_tpu.native import NativeBroker
            broker = NativeBroker(args.host, args.port)
            print(f"native broker on {args.host}:{broker.port}")
        except Exception as e:  # noqa: BLE001 — any build/load failure
            print(f"native broker unavailable ({e}); using Python broker")
    if broker is None:
        from split_learning_tpu.runtime.bus import Broker
        broker = Broker(args.host, args.port)
        print(f"python broker on {args.host}:{broker.port}")
    # SIGTERM (kill, process managers) must tear the native child down
    # with us — a bare kill otherwise orphans it holding the port
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        broker.close()


if __name__ == "__main__":
    main()
