"""``python -m split_learning_tpu.broker`` — standalone message broker.

The reference requires an external RabbitMQ (Erlang) broker
(``/root/reference/README.md:43-69``); this hosts the framework's own
TCP broker instead.  Prefers the native C++ broker when it can be built
(``split_learning_tpu/native``), falling back to the threaded Python one.
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(description="Split-learning TCP broker.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=5672)
    ap.add_argument("--python", action="store_true",
                    help="force the pure-Python broker")
    args = ap.parse_args(argv)

    broker = None
    if not args.python:
        try:
            from split_learning_tpu.native import NativeBroker
            broker = NativeBroker(args.host, args.port)
            print(f"native broker on {args.host}:{broker.port}")
        except Exception as e:  # noqa: BLE001 — any build/load failure
            print(f"native broker unavailable ({e}); using Python broker")
    if broker is None:
        from split_learning_tpu.runtime.bus import Broker
        broker = Broker(args.host, args.port)
        print(f"python broker on {args.host}:{broker.port}")
    # SIGTERM (kill, process managers) must tear the native child down
    # with us — a bare kill otherwise orphans it holding the port
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        broker.close()


if __name__ == "__main__":
    main()
