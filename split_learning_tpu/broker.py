"""``python -m split_learning_tpu.broker`` — standalone message broker.

The reference requires an external RabbitMQ (Erlang) broker
(``/root/reference/README.md:43-69``); this hosts the framework's own
TCP broker instead — a selectors event loop holding O(1) threads per
shard however many connections attach (``runtime/bus.py Broker``).
Prefers the native C++ broker when it can be built
(``split_learning_tpu/native``), falling back to the event-loop Python
one.

``--shards N`` hosts the SHARDED broker plane (``broker.shards``):
this process supervises N shard subprocesses on consecutive ports
``--port .. --port+N-1``, each an independent single-threaded event
loop.  Clients map every queue to its owning shard with the shared
deterministic ``shard_for`` hash, so the plane's aggregate bandwidth
scales with N.  The supervisor forwards SIGTERM/SIGINT and exits when
told to; it deliberately does NOT auto-restart a dead shard — shard
death is a first-class fault the transport layer (per-shard reconnect
backoff + at-least-once redelivery) is paid to survive, and the chaos
suite kills shards to prove it.  Restart one with the printed per-shard
command line.
"""

from __future__ import annotations

import argparse
import signal
import subprocess
import sys
import time


def spawn_shard(host: str, port: int, *, shard_index: int = 0,
                max_frame_gb: float | None = None,
                python_only: bool = False,
                journal_dir: str | None = None) -> subprocess.Popen:
    """Spawn ONE broker shard subprocess bound to ``host:port``.
    Shared by the ``--shards`` supervisor, the broker_shard bench cell
    and the ``--broker-shard`` chaos cell (which SIGKILLs and respawns
    shards through exactly this path).  ``journal_dir`` turns on the
    shard's span journal + flight-recorder dump directory (the same
    artifacts directory the other participants write into)."""
    cmd = [sys.executable, "-m", "split_learning_tpu.broker",
           "--host", host, "--port", str(port),
           "--shard-id", f"shard_{shard_index}@{host}:{port}"]
    if max_frame_gb is not None:
        cmd += ["--max-frame-gb", str(max_frame_gb)]
    if python_only:
        cmd.append("--python")
    if journal_dir is not None:
        cmd += ["--journal-dir", str(journal_dir)]
    return subprocess.Popen(cmd)


def _participant_name(args) -> str:
    """Filesystem-safe participant identity for this shard's span
    journal + blackbox dump (``shard_0@h:p`` → ``broker-shard_0_h_p``)."""
    raw = args.shard_id or f"shard@{args.host}:{args.port}"
    safe = raw.replace("@", "_").replace(":", "_").replace("/", "_")
    return safe if safe.startswith("broker") else f"broker-{safe}"


def _supervise(args) -> int:
    """Host N shard subprocesses; a shard dying on its own is
    reported once and remembered as a non-zero exit code, while the
    surviving shards keep running (partial-plane operation) until an
    operator signal — or the last shard's death — tears the plane
    down.

    Shards are always the PYTHON event-loop broker: the O(1)-thread
    loop and the ``__broker__.stats`` self-telemetry frame are what
    the sharded plane is made of — the native C++ broker speaks the
    frame protocol but answers no stats, which reads as a dead shard
    on every sl_top//fleet sweep."""
    procs = [spawn_shard(args.host, args.port + i, shard_index=i,
                         max_frame_gb=args.max_frame_gb,
                         python_only=True,
                         journal_dir=args.journal_dir)
             for i in range(args.shards)]
    for i in range(args.shards):
        print(f"broker shard {i}/{args.shards} on "
              f"{args.host}:{args.port + i}")
    stop = {"sig": None}

    def on_sig(signum, _frame):
        stop["sig"] = signum

    signal.signal(signal.SIGTERM, on_sig)
    signal.signal(signal.SIGINT, on_sig)
    rc = 0
    dead: set = set()
    try:
        while stop["sig"] is None:
            for i, p in enumerate(procs):
                code = p.poll()
                if code is not None and i not in dead:
                    # reported ONCE per shard; the supervisor keeps
                    # the surviving shards up (partial-plane operation
                    # is the resilience story) and remembers the
                    # non-zero exit for when it is torn down
                    dead.add(i)
                    print(f"broker shard {i} exited rc={code} "
                          f"(restart: {sys.executable} -m "
                          f"split_learning_tpu.broker --host "
                          f"{args.host} --port {args.port + i})",
                          file=sys.stderr)
                    rc = 1
            if len(dead) == args.shards:
                print("all broker shards exited; stopping",
                      file=sys.stderr)
                break
            time.sleep(0.5)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(description="Split-learning TCP broker.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=5672)
    ap.add_argument("--shards", type=int, default=1,
                    help="host a sharded broker plane: N shard "
                         "subprocesses on ports --port..--port+N-1 "
                         "(broker.shards in config.yaml); every queue "
                         "is owned by exactly one shard via the "
                         "deterministic shard_for hash")
    ap.add_argument("--shard-id", default=None,
                    help="stats-frame identity of this shard "
                         "(set by the --shards supervisor)")
    ap.add_argument("--python", action="store_true",
                    help="force the pure-Python broker")
    ap.add_argument("--journal-dir", default=None,
                    help="artifacts directory: turns on this shard's "
                         "span journal (spans-<shard>.jsonl broker.tick "
                         "heartbeat spans) and flight-recorder dump "
                         "directory (blackbox-<shard>.json on abnormal "
                         "exit).  Implies --python — the native broker "
                         "has neither plane")
    ap.add_argument("--max-frame-gb", type=float, default=None,
                    help="per-frame payload cap (default 8 GiB): a "
                         "corrupt length prefix fails the connection "
                         "instead of driving a huge allocation.  "
                         "Enforced by the pure-Python broker only "
                         "(implies --python); publishers fail-fast "
                         "against their own process's cap, so lower "
                         "it on both sides or oversized publishes "
                         "die at the broker instead of the client")
    args = ap.parse_args(argv)

    if args.shards > 1:
        return _supervise(args)

    if args.journal_dir is not None and not args.python:
        # only the Python event-loop broker carries the tracer +
        # flight-recorder planes
        args.python = True

    if args.max_frame_gb is not None:
        from split_learning_tpu.runtime import bus, protocol
        bus.MAX_FRAME_BYTES = int(args.max_frame_gb * (1 << 30))
        # the chunked twin lives at the ENDPOINTS: reassembly happens
        # in each server/client/aggregator process's FrameAssembler,
        # which this process cannot reach — set SLT_MAX_ASSEMBLED_GB
        # in those processes' environments to lower it there (counted
        # oversize_frames).  Lowered here too for a broker-hosted
        # server (--broker in the server process).
        protocol.MAX_ASSEMBLED_BYTES = bus.MAX_FRAME_BYTES
        if not args.python:
            print("--max-frame-gb: native broker does not enforce the "
                  "cap; using the Python broker")
            args.python = True

    broker = None
    if not args.python:
        try:
            from split_learning_tpu.native import NativeBroker
            broker = NativeBroker(args.host, args.port)
            print(f"native broker on {args.host}:{broker.port}")
        except Exception as e:  # noqa: BLE001 — any build/load failure
            print(f"native broker unavailable ({e}); using Python broker")
    if broker is None:
        from split_learning_tpu.runtime.bus import Broker
        tracer = None
        if args.journal_dir is not None:
            from split_learning_tpu.runtime.spans import Tracer
            tracer = Tracer(_participant_name(args),
                            journal_dir=args.journal_dir)
        broker = Broker(args.host, args.port, shard_id=args.shard_id,
                        tracer=tracer)
        print(f"python broker on {args.host}:{broker.port} "
              f"(event loop, 1 thread)", flush=True)
    # SIGTERM (kill, process managers) must tear the native child down
    # with us — a bare kill otherwise orphans it holding the port
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    if args.journal_dir is not None:
        # AFTER the clean-exit lambda: the flight recorder's SIGTERM
        # handler dumps blackbox-<shard>.json then CHAINS to it, so a
        # plain kill still tears the broker down via sys.exit(0)
        from split_learning_tpu.runtime import blackbox
        blackbox.install_basic(_participant_name(args),
                               role="broker_shard",
                               dump_dir=args.journal_dir)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        broker.close()


if __name__ == "__main__":
    sys.exit(main() or 0)
