"""``python -m split_learning_tpu.server`` — protocol server entry
(reference ``server.py`` parity)."""

from split_learning_tpu.runtime.server import main

if __name__ == "__main__":
    main()
