"""``python -m split_learning_tpu.client`` — protocol client entry
(reference ``client.py`` parity)."""

from split_learning_tpu.runtime.client import main

if __name__ == "__main__":
    main()
