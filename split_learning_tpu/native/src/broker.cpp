// slt_broker — native message broker for the split-learning control and
// data plane.
//
// The reference deployment depends on an external RabbitMQ broker (an
// Erlang process, /root/reference/README.md:43-69); this is the
// framework's own native equivalent: a single-threaded poll(2) event
// loop speaking the same length-prefixed frame protocol as the Python
// Transport (split_learning_tpu/runtime/bus.py):
//
//   request:  op(1) | name_len(4 BE) | name | payload_len(8 BE) | payload
//     op 'P' publish: name = queue, payload = message bytes
//     op 'G' get:     name = queue, payload = 8-byte BE timeout ms
//                     (0 = block forever)
//     op 'X' purge:   payload = comma-separated queue names ("" = all)
//   reply ('G' only): 'R' | 0(4 BE) | payload_len(8 BE) | payload
//     timeout signalled by payload_len == 0xFFFFFFFFFFFFFFFF, no bytes.
//
// Blocking GETs park the connection on a FIFO waiter list per queue;
// a PUBLISH hands the message straight to the oldest live waiter
// (never touching the queue), so latency under load is one event-loop
// turn.  One outstanding request per connection (the Python client
// serializes under a lock), messages delivered at-least-once in FIFO
// order per queue.
//
// Build: g++ -O2 -std=c++17 -o slt_broker broker.cpp
// Run:   slt_broker [port]   (0 = ephemeral; prints "LISTENING <port>")

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstdio>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint64_t kTimeoutSentinel = 0xFFFFFFFFFFFFFFFFull;
// Hard sanity caps: a desynced client must kill its connection, not the
// broker (length arithmetic is checked against these before any alloc).
constexpr uint64_t kMaxName = 1 << 16;         // 64 KiB queue name
constexpr uint64_t kMaxPayload = 1ull << 32;   // 4 GiB message

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Conn {
  int fd = -1;
  std::vector<uint8_t> in;   // partial inbound frame bytes
  std::deque<uint8_t> out;   // pending outbound bytes
  bool waiting = false;      // parked on a blocking GET
  std::string wait_queue;
  double deadline = 0.0;     // 0 = no deadline (wait forever)
  bool dead = false;
};

struct Broker {
  std::unordered_map<int, Conn> conns;
  std::unordered_map<std::string, std::deque<std::string>> queues;
  // FIFO of fds parked on each queue
  std::unordered_map<std::string, std::deque<int>> waiters;

  static void put32(std::string* b, uint32_t v) {
    uint32_t n = htonl(v);
    b->append(reinterpret_cast<char*>(&n), 4);
  }
  static void put64(std::string* b, uint64_t v) {
    for (int i = 7; i >= 0; --i)
      b->push_back(static_cast<char>((v >> (8 * i)) & 0xff));
  }
  static uint32_t get32(const uint8_t* p) {
    uint32_t v;
    memcpy(&v, p, 4);
    return ntohl(v);
  }
  static uint64_t get64(const uint8_t* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
    return v;
  }

  void send_reply(Conn* c, const std::string* payload) {
    std::string frame;
    frame.push_back('R');
    put32(&frame, 0);
    if (payload == nullptr) {
      put64(&frame, kTimeoutSentinel);
    } else {
      put64(&frame, payload->size());
      frame += *payload;
    }
    c->out.insert(c->out.end(), frame.begin(), frame.end());
  }

  // Deliver one message to the oldest live waiter of `queue`.
  // Returns false if no live waiter took it.
  bool hand_to_waiter(const std::string& queue, const std::string& msg) {
    auto it = waiters.find(queue);
    if (it == waiters.end()) return false;
    auto& fifo = it->second;
    while (!fifo.empty()) {
      int fd = fifo.front();
      fifo.pop_front();
      auto cit = conns.find(fd);
      if (cit == conns.end() || cit->second.dead ||
          !cit->second.waiting || cit->second.wait_queue != queue)
        continue;
      cit->second.waiting = false;
      send_reply(&cit->second, &msg);
      return true;
    }
    return false;
  }

  void handle_frame(Conn* c, uint8_t op, std::string name,
                    std::string payload) {
    if (op == 'P') {
      if (!hand_to_waiter(name, payload))
        queues[name].push_back(std::move(payload));
    } else if (op == 'G') {
      uint64_t ms = payload.size() >= 8
                        ? get64(reinterpret_cast<const uint8_t*>(
                              payload.data()))
                        : 0;
      auto qit = queues.find(name);
      if (qit != queues.end() && !qit->second.empty()) {
        send_reply(c, &qit->second.front());
        qit->second.pop_front();
      } else {
        c->waiting = true;
        c->wait_queue = name;
        c->deadline = ms == 0 ? 0.0 : now_s() + ms / 1000.0;
        waiters[name].push_back(c->fd);
      }
    } else if (op == 'X') {
      if (payload.empty()) {
        queues.clear();
      } else {
        size_t start = 0;
        while (start <= payload.size()) {
          size_t comma = payload.find(',', start);
          std::string q = payload.substr(
              start, comma == std::string::npos ? std::string::npos
                                                : comma - start);
          queues.erase(q);
          if (comma == std::string::npos) break;
          start = comma + 1;
        }
      }
    }
  }

  // Parse as many complete frames as `c->in` holds.
  void drain_input(Conn* c) {
    size_t off = 0;
    while (true) {
      const size_t have = c->in.size() - off;
      if (have < 1 + 4) break;
      const uint8_t* p = c->in.data() + off;
      uint32_t nlen = get32(p + 1);
      if (nlen > kMaxName) {        // desynced/hostile framing
        c->dead = true;
        break;
      }
      if (have < 1 + 4 + nlen + 8) break;
      uint64_t plen = get64(p + 1 + 4 + nlen);
      if (plen == kTimeoutSentinel) plen = 0;
      if (plen > kMaxPayload) {     // also guards length-sum overflow
        c->dead = true;
        break;
      }
      if (have < 1 + 4 + nlen + 8 + plen) break;
      std::string name(reinterpret_cast<const char*>(p + 5), nlen);
      std::string payload(
          reinterpret_cast<const char*>(p + 5 + nlen + 8), plen);
      uint8_t op = p[0];
      off += 1 + 4 + nlen + 8 + plen;
      handle_frame(c, op, std::move(name), std::move(payload));
    }
    if (off > 0) c->in.erase(c->in.begin(), c->in.begin() + off);
  }

  void remove_waiter(int fd, const std::string& queue) {
    auto it = waiters.find(queue);
    if (it == waiters.end()) return;
    auto& fifo = it->second;
    for (auto w = fifo.begin(); w != fifo.end(); ++w) {
      if (*w == fd) {
        fifo.erase(w);
        break;
      }
    }
    if (fifo.empty()) waiters.erase(it);
  }

  void expire_waiters() {
    double t = now_s();
    for (auto& [fd, c] : conns) {
      if (c.waiting && c.deadline > 0.0 && t >= c.deadline) {
        c.waiting = false;
        remove_waiter(fd, c.wait_queue);
        send_reply(&c, nullptr);
      }
    }
  }

  int poll_timeout_ms() const {
    double best = -1.0;
    double t = now_s();
    for (const auto& [fd, c] : conns) {
      if (c.waiting && c.deadline > 0.0) {
        double remain = c.deadline - t;
        if (remain < 0) remain = 0;
        if (best < 0 || remain < best) best = remain;
      }
    }
    if (best < 0) return 1000;
    int ms = static_cast<int>(best * 1000) + 1;
    return ms > 1000 ? 1000 : ms;
  }
};

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 5672;

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return perror("socket"), 1;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
    return perror("bind"), 1;
  if (listen(lfd, 128) < 0) return perror("listen"), 1;
  socklen_t alen = sizeof(addr);
  getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  printf("LISTENING %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  Broker broker;
  std::vector<pollfd> pfds;
  std::vector<uint8_t> buf(1 << 20);

  while (true) {
    pfds.clear();
    pfds.push_back({lfd, POLLIN, 0});
    for (auto& [fd, c] : broker.conns) {
      short ev = POLLIN;
      if (!c.out.empty()) ev |= POLLOUT;
      pfds.push_back({fd, ev, 0});
    }
    int rc = poll(pfds.data(), pfds.size(), broker.poll_timeout_ms());
    if (rc < 0) {
      if (errno == EINTR) continue;
      return perror("poll"), 1;
    }
    broker.expire_waiters();

    if (pfds[0].revents & POLLIN) {
      int cfd = accept(lfd, nullptr, nullptr);
      if (cfd >= 0) {
        setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        fcntl(cfd, F_SETFL, fcntl(cfd, F_GETFL, 0) | O_NONBLOCK);
        Conn c;
        c.fd = cfd;
        broker.conns.emplace(cfd, std::move(c));
      }
    }

    std::vector<int> closed;
    for (size_t i = 1; i < pfds.size(); ++i) {
      auto it = broker.conns.find(pfds[i].fd);
      if (it == broker.conns.end()) continue;
      Conn& c = it->second;
      if (pfds[i].revents & (POLLERR | POLLHUP)) {
        c.dead = true;
        closed.push_back(c.fd);
        continue;
      }
      if (pfds[i].revents & POLLIN) {
        // drain everything available (non-blocking socket)
        while (true) {
          ssize_t n = read(c.fd, buf.data(), buf.size());
          if (n > 0) {
            c.in.insert(c.in.end(), buf.data(), buf.data() + n);
            if (static_cast<size_t>(n) < buf.size()) break;
            continue;
          }
          if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
          c.dead = true;
          closed.push_back(c.fd);
          break;
        }
        if (!c.dead) broker.drain_input(&c);
      }
    }
    // flush every connection with pending output NOW — replies created
    // this iteration must not wait out the next poll timeout
    for (auto& [fd, c] : broker.conns) {
      while (!c.dead && !c.out.empty()) {
        std::vector<uint8_t> chunk(c.out.begin(),
                                   c.out.begin() +
                                       std::min(c.out.size(), buf.size()));
        ssize_t n = write(c.fd, chunk.data(), chunk.size());
        if (n > 0) {
          c.out.erase(c.out.begin(), c.out.begin() + n);
          continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
          break;  // kernel buffer full: POLLOUT will resume it
        c.dead = true;
        closed.push_back(c.fd);
        break;
      }
    }
    for (int fd : closed) {
      auto it = broker.conns.find(fd);
      if (it != broker.conns.end() && it->second.waiting)
        broker.remove_waiter(fd, it->second.wait_queue);
      close(fd);
      broker.conns.erase(fd);
    }
  }
}
