// slt_mfcc — native MFCC feature extraction for the SpeechCommands data
// path.
//
// Same math as the Python pipeline (split_learning_tpu/data/mfcc.py,
// itself parity with the reference's manual numpy/scipy chain,
// /root/reference/src/dataset/SPEECHCOMMANDS.py:11-47): pre-emphasis,
// 25/10 ms framing, Hamming window, radix-2 real FFT power spectrum,
// triangular mel filterbank (floor-binned), log, DCT-II with ortho
// normalization.  Double precision internally so outputs match the
// numpy float64 pipeline to ~1e-6.
//
// C ABI (ctypes):
//   int slt_mfcc_batch(const float* signals, int batch, int n_samples,
//                      int sample_rate, int n_mfcc, double frame_ms,
//                      double hop_ms, int n_fft, int n_mels,
//                      double pre_emphasis, float* out, int* n_frames_out)
// out must hold batch * n_mfcc * n_frames floats; returns 0 on success.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -o libslt_mfcc.so mfcc.cpp

#include <cmath>
#include <cstring>
#include <vector>

namespace {

constexpr double kPi = 3.14159265358979323846;

// In-place iterative radix-2 complex FFT (n must be a power of two).
void fft(std::vector<double>& re, std::vector<double>& im) {
  const size_t n = re.size();
  for (size_t i = 1, j = 0; i < n; ++i) {  // bit reversal
    size_t bit = n >> 1;
    for (; j & bit; bit >>= 1) j ^= bit;
    j ^= bit;
    if (i < j) {
      std::swap(re[i], re[j]);
      std::swap(im[i], im[j]);
    }
  }
  for (size_t len = 2; len <= n; len <<= 1) {
    const double ang = -2.0 * kPi / static_cast<double>(len);
    const double wr = std::cos(ang), wi = std::sin(ang);
    for (size_t i = 0; i < n; i += len) {
      double cr = 1.0, ci = 0.0;
      for (size_t k = 0; k < len / 2; ++k) {
        const size_t a = i + k, b = i + k + len / 2;
        const double tr = re[b] * cr - im[b] * ci;
        const double ti = re[b] * ci + im[b] * cr;
        re[b] = re[a] - tr;
        im[b] = im[a] - ti;
        re[a] += tr;
        im[a] += ti;
        const double ncr = cr * wr - ci * wi;
        ci = cr * wi + ci * wr;
        cr = ncr;
      }
    }
  }
}

double hz_to_mel(double hz) { return 2595.0 * std::log10(1.0 + hz / 700.0); }
double mel_to_hz(double mel) {
  return 700.0 * (std::pow(10.0, mel / 2595.0) - 1.0);
}

// (n_mels, n_fft/2+1) triangular filterbank, floor-binned like the
// Python mel_filterbank.
std::vector<double> filterbank(int n_mels, int n_fft, int sample_rate) {
  const int n_bins = n_fft / 2 + 1;
  std::vector<double> fb(static_cast<size_t>(n_mels) * n_bins, 0.0);
  std::vector<int> bins(n_mels + 2);
  const double mel_lo = hz_to_mel(0.0);
  const double mel_hi = hz_to_mel(sample_rate / 2.0);
  for (int m = 0; m < n_mels + 2; ++m) {
    const double mel = mel_lo + (mel_hi - mel_lo) * m / (n_mels + 1);
    bins[m] = static_cast<int>(
        std::floor((n_fft + 1) * mel_to_hz(mel) / sample_rate));
  }
  for (int m = 1; m <= n_mels; ++m) {
    const int lo = bins[m - 1], ctr = bins[m], hi = bins[m + 1];
    for (int k = lo; k < ctr; ++k)
      if (ctr > lo) fb[(m - 1) * n_bins + k] =
          static_cast<double>(k - lo) / (ctr - lo);
    for (int k = ctr; k < hi; ++k)
      if (hi > ctr) fb[(m - 1) * n_bins + k] =
          static_cast<double>(hi - k) / (hi - ctr);
  }
  return fb;
}

}  // namespace

extern "C" int slt_mfcc_batch(const float* signals, int batch,
                              int n_samples, int sample_rate, int n_mfcc,
                              double frame_ms, double hop_ms, int n_fft,
                              int n_mels, double pre_emphasis, float* out,
                              int* n_frames_out) {
  if ((n_fft & (n_fft - 1)) != 0 || n_fft <= 0) return 1;  // power of two
  const int frame_len =
      static_cast<int>(std::lround(sample_rate * frame_ms / 1000.0));
  const int hop =
      static_cast<int>(std::lround(sample_rate * hop_ms / 1000.0));
  if (frame_len <= 0 || hop <= 0 || frame_len > n_fft) return 2;
  const int n_frames =
      n_samples >= frame_len
          ? 1 + (n_samples - frame_len) / hop
          : 1;
  *n_frames_out = n_frames;
  const int n_bins = n_fft / 2 + 1;

  std::vector<double> hamming(frame_len);
  for (int i = 0; i < frame_len; ++i)
    hamming[i] = 0.54 - 0.46 * std::cos(2.0 * kPi * i / (frame_len - 1));
  const std::vector<double> fb = filterbank(n_mels, n_fft, sample_rate);

  // DCT-II ortho basis: (n_mfcc, n_mels)
  std::vector<double> dct(static_cast<size_t>(n_mfcc) * n_mels);
  for (int k = 0; k < n_mfcc; ++k) {
    const double scale =
        k == 0 ? std::sqrt(1.0 / n_mels) : std::sqrt(2.0 / n_mels);
    for (int i = 0; i < n_mels; ++i)
      dct[k * n_mels + i] =
          scale * std::cos(kPi * k * (2 * i + 1) / (2.0 * n_mels));
  }

  // sparse filterbank: each mel touches only its triangle's bins
  std::vector<int> mel_lo(n_mels), mel_hi(n_mels);
  {
    for (int m = 0; m < n_mels; ++m) {
      int lo = n_bins, hi = 0;
      for (int k = 0; k < n_bins; ++k)
        if (fb[static_cast<size_t>(m) * n_bins + k] != 0.0) {
          if (k < lo) lo = k;
          hi = k + 1;
        }
      mel_lo[m] = lo < n_bins ? lo : 0;
      mel_hi[m] = hi;
    }
  }

  std::vector<double> sig(n_samples);
  std::vector<double> re(n_fft), im(n_fft);
  std::vector<double> power(n_bins);
  std::vector<double> mel(n_mels);

  for (int b = 0; b < batch; ++b) {
    const float* s = signals + static_cast<size_t>(b) * n_samples;
    sig[0] = s[0];
    for (int i = 1; i < n_samples; ++i)
      sig[i] = s[i] - pre_emphasis * s[i - 1];

    float* o = out + static_cast<size_t>(b) * n_mfcc * n_frames;
    for (int f = 0; f < n_frames; ++f) {
      const int start = f * hop;
      std::fill(re.begin() + frame_len, re.end(), 0.0);
      std::fill(im.begin(), im.end(), 0.0);
      for (int i = 0; i < frame_len; ++i) {
        const int src = start + i;
        re[i] = (src < n_samples ? sig[src] : 0.0) * hamming[i];
      }
      fft(re, im);
      for (int k = 0; k < n_bins; ++k)
        power[k] = (re[k] * re[k] + im[k] * im[k]) / n_fft;
      for (int m = 0; m < n_mels; ++m) {
        double acc = 0.0;
        const double* w = fb.data() + static_cast<size_t>(m) * n_bins;
        for (int k = mel_lo[m]; k < mel_hi[m]; ++k) acc += w[k] * power[k];
        mel[m] = std::log(acc + 1e-10);
      }
      for (int k = 0; k < n_mfcc; ++k) {
        double acc = 0.0;
        for (int m = 0; m < n_mels; ++m)
          acc += dct[k * n_mels + m] * mel[m];
        o[k * n_frames + f] = static_cast<float>(acc);
      }
    }
  }
  return 0;
}
