"""Native runtime components (C++), built on demand with the system
toolchain.

``NativeBroker`` wraps ``native/broker.cpp`` — the framework's native
message broker (the role RabbitMQ plays for the reference,
``/root/reference/README.md:43-69``): compile (cached by source mtime),
spawn as a subprocess, parse the bound port, and manage lifetime.  The
Python ``TcpTransport`` speaks to it unchanged; ``python -m
split_learning_tpu.broker`` prefers it and falls back to the threaded
Python broker when no compiler is available.
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess

_ROOT = pathlib.Path(__file__).resolve().parents[2]
_SRC = _ROOT / "native" / "broker.cpp"
_BIN_DIR = _ROOT / "native" / "bin"
_BIN = _BIN_DIR / "slt_broker"


class NativeBuildError(RuntimeError):
    pass


def build_broker(force: bool = False) -> pathlib.Path:
    """Compile the broker if the cached binary is missing or stale."""
    if not _SRC.exists():
        raise NativeBuildError(f"missing source {_SRC}")
    if not force and _BIN.exists() \
            and _BIN.stat().st_mtime >= _SRC.stat().st_mtime:
        return _BIN
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        raise NativeBuildError("no C++ compiler on PATH")
    _BIN_DIR.mkdir(parents=True, exist_ok=True)
    cmd = [gxx, "-O2", "-std=c++17", "-o", str(_BIN), str(_SRC)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"broker build failed:\n{proc.stderr[-2000:]}")
    return _BIN


class NativeBroker:
    """A running native broker subprocess."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        if host not in ("127.0.0.1", "localhost"):
            raise NativeBuildError("native broker binds loopback only")
        binary = build_broker()
        self._proc = subprocess.Popen(
            [str(binary), str(port)], stdout=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline().strip()
        if not line.startswith("LISTENING "):
            self._proc.kill()
            raise NativeBuildError(f"unexpected broker banner {line!r}")
        self.host = host
        self.port = int(line.split()[1])

    def close(self):
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
