"""Native runtime components (C++), built on demand with the system
toolchain.

``NativeBroker`` wraps ``src/broker.cpp`` (shipped as package data so
installed distributions can build it too) — the framework's native
message broker (the role RabbitMQ plays for the reference,
``/root/reference/README.md:43-69``): compile (cached by source mtime),
spawn as a subprocess, parse the bound port, and manage lifetime.  The
Python ``TcpTransport`` speaks to it unchanged; ``python -m
split_learning_tpu.broker`` prefers it and falls back to the threaded
Python broker when no compiler is available.

Built artifacts go next to the sources when that directory is writable
(source checkout), else to ``~/.cache/split_learning_tpu/bin``
(site-packages installs are often read-only).
"""

from __future__ import annotations

import os
import pathlib
import shutil
import subprocess

_SRC_DIR = pathlib.Path(__file__).resolve().parent / "src"
_SRC = _SRC_DIR / "broker.cpp"
_MFCC_SRC = _SRC_DIR / "mfcc.cpp"


def _bin_dir() -> pathlib.Path:
    override = os.environ.get("SLT_NATIVE_BIN")
    if override:
        return pathlib.Path(override)
    local = _SRC_DIR.parent / "bin"
    try:
        local.mkdir(parents=True, exist_ok=True)
        probe = local / ".writable"
        probe.touch()
        probe.unlink()
        return local
    except OSError:
        return pathlib.Path.home() / ".cache" / "split_learning_tpu" / "bin"


_BIN_DIR = _bin_dir()
_BIN = _BIN_DIR / "slt_broker"
_MFCC_LIB = _BIN_DIR / "libslt_mfcc.so"


class NativeBuildError(RuntimeError):
    pass


def _compiler() -> str:
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        raise NativeBuildError("no C++ compiler on PATH")
    return gxx


def _build(src: pathlib.Path, dest: pathlib.Path,
           extra: list | None = None, force: bool = False) -> pathlib.Path:
    """Compile ``src`` -> ``dest`` unless the cached artifact is fresh."""
    if not src.exists():
        raise NativeBuildError(f"missing source {src}")
    if not force and dest.exists() \
            and dest.stat().st_mtime >= src.stat().st_mtime:
        return dest
    try:
        _BIN_DIR.mkdir(parents=True, exist_ok=True)
    except OSError as e:
        raise NativeBuildError(f"cannot create bin dir {_BIN_DIR}: {e}")
    cmd = [_compiler(), "-O2", "-std=c++17", *(extra or []),
           "-o", str(dest), str(src)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"build of {src.name} failed:\n{proc.stderr[-2000:]}")
    return dest


def build_broker(force: bool = False) -> pathlib.Path:
    return _build(_SRC, _BIN, force=force)


def build_mfcc(force: bool = False) -> pathlib.Path:
    return _build(_MFCC_SRC, _MFCC_LIB,
                  extra=["-O3", "-shared", "-fPIC"], force=force)


_mfcc_lib = None


def mfcc_batch_native(signals, sample_rate: int = 16000, n_mfcc: int = 40,
                      frame_ms: float = 25.0, hop_ms: float = 10.0,
                      n_fft: int = 512, n_mels: int = 64,
                      pre_emphasis: float = 0.97):
    """(B, n_mfcc, n_frames) MFCCs via the C++ extractor.

    Raises :class:`NativeBuildError` when no compiler is available —
    callers fall back to the numpy pipeline (``data/mfcc.py``).
    """
    import ctypes

    import numpy as np

    global _mfcc_lib
    if _mfcc_lib is None:
        lib = ctypes.CDLL(str(build_mfcc()))
        lib.slt_mfcc_batch.restype = ctypes.c_int
        lib.slt_mfcc_batch.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_double,
            ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int),
        ]
        _mfcc_lib = lib

    sig = np.ascontiguousarray(signals, dtype=np.float32)
    if sig.ndim == 1:
        sig = sig[None]
    batch, n_samples = sig.shape
    frame_len = int(round(sample_rate * frame_ms / 1000.0))
    hop = int(round(sample_rate * hop_ms / 1000.0))
    n_frames = max(1, 1 + (n_samples - frame_len) // hop)
    out = np.empty((batch, n_mfcc, n_frames), dtype=np.float32)
    got_frames = ctypes.c_int(0)
    rc = _mfcc_lib.slt_mfcc_batch(
        sig.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        batch, n_samples, sample_rate, n_mfcc, frame_ms, hop_ms,
        n_fft, n_mels, pre_emphasis,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(got_frames))
    if rc != 0 or got_frames.value != n_frames:
        raise NativeBuildError(f"slt_mfcc_batch failed rc={rc}")
    return out


class NativeBroker:
    """A running native broker subprocess."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        if host not in ("127.0.0.1", "localhost"):
            raise NativeBuildError("native broker binds loopback only")
        binary = build_broker()
        self._proc = subprocess.Popen(
            [str(binary), str(port)], stdout=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline().strip()
        if not line.startswith("LISTENING "):
            self._proc.kill()
            raise NativeBuildError(f"unexpected broker banner {line!r}")
        self.host = host
        self.port = int(line.split()[1])

    def close(self):
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
