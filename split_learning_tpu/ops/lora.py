"""Parameter-level LoRA adapters (peft parity).

The reference wraps BERT shards with HF peft LoRA (r=8, alpha=16, targets
query/key/value/dense) at START, trains only the adapters (+ the
classifier on the last stage), and bakes them into the weights with
``merge_and_unload`` before UPDATE
(``/root/reference/src/RpcClient.py:61-66``, ``:99-103``, ``:121-122``).

Here LoRA lives at the parameter-pytree level, independent of module
internals: for every kernel whose path matches a target name, keep a pair
of factors ``a: (in, r)``, ``b: (r, out)``; the effective weight is
``W + (alpha/r) a @ b``.  This composes with ANY flax model in the zoo
(fused-qkv attention included — DenseGeneral kernels are treated as 2-D
by flattening the head dims) and with the split/pipeline machinery, since
adapters are just another pytree sliced by layer name.

Training trains the adapter tree (plus an optional unfrozen set) while
the base params stay constant: differentiate the merged apply w.r.t. the
adapter tree only — exactly peft's semantics, not a masked update.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from split_learning_tpu.config import (
    LORA_DEFAULT_TARGETS as DEFAULT_TARGETS,
)


def _path_names(path) -> list:
    names = []
    for p in path:
        if hasattr(p, "key"):
            names.append(str(p.key))
        elif hasattr(p, "idx"):
            names.append(str(p.idx))
        else:
            names.append(str(p))
    return names


def _is_target(path, leaf, targets) -> bool:
    names = _path_names(path)
    if not names or names[-1] != "kernel":
        return False
    if np.ndim(leaf) < 2:
        return False
    return any(t in names for t in targets)


def _as_2d(shape: Sequence[int], names: Sequence[str]) -> tuple[int, int]:
    """(in, out) view of a kernel.

    Flax MHA q/k/v kernels are (embed, heads, head_dim) — input first,
    fused heads on the OUTPUT side; the out-projection kernel is
    (heads, head_dim, embed) — heads on the INPUT side.  Getting this
    wrong would factor the wrong matrix (a (heads, r) x (r, head_dim*embed)
    pair instead of rank-r over the real (in, out))."""
    if len(shape) <= 2:
        return int(shape[0]), int(np.prod(shape[1:]))
    if "out" in names:
        return int(np.prod(shape[:-1])), int(shape[-1])
    return int(shape[0]), int(np.prod(shape[1:]))


def lora_init(rng, params, targets: Sequence[str] = DEFAULT_TARGETS,
              rank: int = 8) -> dict:
    """Adapter tree mirroring ``params``: matched kernels get
    ``{"a", "b"}``, everything else an empty placeholder pruned from the
    tree.  ``a`` is Gaussian/r, ``b`` zeros — so the merged model starts
    exactly at the base weights (peft init)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out: dict = {}
    keys = jax.random.split(rng, max(1, len(flat)))
    for (path, leaf), k in zip(flat, keys):
        if not _is_target(path, leaf, targets):
            continue
        names = _path_names(path)
        d_in, d_out = _as_2d(np.shape(leaf), names)
        node = out
        for name in names[:-1]:
            node = node.setdefault(name, {})
        node[names[-1]] = {
            "a": (jax.random.normal(k, (d_in, rank),
                                    jnp.asarray(leaf).dtype) / rank),
            "b": jnp.zeros((rank, d_out), jnp.asarray(leaf).dtype),
        }
    return out


def _lookup(tree: dict, names: list):
    node = tree
    for n in names:
        if not isinstance(node, dict) or n not in node:
            return None
        node = node[n]
    return node


def lora_merge(params, lora: dict, alpha: float = 16.0,
               rank: int = 8):
    """Bake adapters into the base weights: ``W + (alpha/r) a @ b``
    (peft ``merge_and_unload``)."""
    scale = alpha / rank

    def merge_leaf(path, leaf):
        entry = _lookup(lora, _path_names(path))
        if not (isinstance(entry, dict) and "a" in entry and "b" in entry):
            return leaf
        delta = (entry["a"] @ entry["b"]).reshape(np.shape(leaf))
        return leaf + scale * delta.astype(jnp.asarray(leaf).dtype)

    return jax.tree_util.tree_map_with_path(merge_leaf, params)


def lora_param_count(lora: dict) -> int:
    return sum(int(np.prod(np.shape(leaf)))
               for leaf in jax.tree_util.tree_leaves(lora))


def split_frozen(params, unfrozen_names: Sequence[str]):
    """Partition a param tree into (frozen, trainable) by top-level layer
    name — the reference unfreezes the classifier head on the last stage
    (``src/RpcClient.py:101-103``)."""
    frozen = {k: v for k, v in params.items() if k not in unfrozen_names}
    trainable = {k: v for k, v in params.items() if k in unfrozen_names}
    return frozen, trainable
