"""Numerical ops: aggregation, pipelined collectives, attention kernels."""
