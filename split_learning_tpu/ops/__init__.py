"""Numerical ops: aggregation, attention kernels, LoRA adapters."""

from split_learning_tpu.ops.fedavg import fedavg_psum, fedavg_trees
from split_learning_tpu.ops.flash_attention import flash_attention
from split_learning_tpu.ops.lora import lora_init, lora_merge, split_frozen

__all__ = [
    "fedavg_psum", "fedavg_trees", "flash_attention",
    "lora_init", "lora_merge", "split_frozen",
]
