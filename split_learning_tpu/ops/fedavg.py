"""Weighted FedAvg over parameter pytrees.

Behavioral parity with the reference aggregator
(``/root/reference/src/Utils.py:35-66``), re-expressed over JAX pytrees:

* weighted average with weights normalized by the *total* weight (absent
  contributors still dilute — the reference divides by ``sum(weights)`` even
  for keys only some clients have);
* union of keys across contributors (a key missing from a client simply
  contributes nothing);
* NaNs zero-filled before averaging;
* integer/bool leaves are averaged in float then rounded back to the original
  dtype.

Two forms: a host-side tree fold (used at round barriers by the orchestrator,
mirrors the server's UPDATE handling) and an in-mesh form
(:func:`fedavg_psum`) that runs the same weighted mean as a ``psum`` over a
mesh axis inside a jitted step — the TPU-native path where all clients of a
stage live on devices of one slice.

:func:`fedavg_psum` is layout-agnostic: the "tree" may equally be the
flat stage-sliced parameter wire of
:func:`split_learning_tpu.parallel.pipeline.make_sliced_train_step` —
the psum stays over ``client`` and each device folds only its own
stage slice (``make_fedavg_step(mesh, param_spec=P("client",
"stage"))``), so the round barrier inherits the sliced layout's 1/A
per-device traffic for free.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp


def is_int_dtype(dtype) -> bool:
    """True for integer/bool dtypes (jnp or np): these average in
    float then round back — shared by every fold implementation so
    they can never disagree on the rounding path."""
    return (jnp.issubdtype(dtype, jnp.integer)
            or jnp.issubdtype(dtype, jnp.bool_))


_is_int_dtype = is_int_dtype


def _avg_leaves(leaves: Sequence[jnp.ndarray], weights: Sequence[float],
                total_w: float) -> jnp.ndarray:
    orig_dtype = leaves[0].dtype
    acc = None
    for leaf, w in zip(leaves, weights):
        t = jnp.nan_to_num(jnp.asarray(leaf, dtype=jnp.float32)) * w
        acc = t if acc is None else acc + t
    avg = acc / total_w
    if _is_int_dtype(orig_dtype):
        return jnp.round(avg).astype(orig_dtype)
    return avg.astype(orig_dtype)


def fedavg_trees(trees: Sequence[Any],
                 weights: Sequence[float] | None = None) -> Any:
    """Weighted FedAvg over a list of pytrees (flat or nested dicts).

    Dict nodes are merged by key union; non-dict leaves are averaged.  Shapes
    of shared leaves must match (the reference has the same constraint — it
    adds tensors elementwise).
    """
    if not trees:
        raise ValueError("fedavg_trees: empty input")
    if weights is None:
        weights = [1.0] * len(trees)
    total_w = float(sum(weights))

    def merge(nodes_weights):
        nodes = [n for n, _ in nodes_weights]
        if isinstance(nodes[0], dict):
            keys = set().union(*(n.keys() for n in nodes))
            return {
                k: merge([(n[k], w) for n, w in nodes_weights if k in n])
                for k in sorted(keys)
            }
        ws = [w for _, w in nodes_weights]
        return _avg_leaves(nodes, ws, total_w)

    return merge(list(zip(trees, weights)))


def walk_items(tree: Any, prefix: tuple = ()):
    """(path, leaf) pairs under ``fedavg_trees`` dict semantics: dicts
    are internal nodes, everything else is a leaf.  The ONE canonical
    tree walk both fold implementations share — :class:`TreeFold`
    (the reference oracle) and the runtime's
    :class:`~split_learning_tpu.runtime.aggregate.StreamingFold` — so
    their bit-identity contract cannot be broken by the two sides
    disagreeing about what a leaf is."""
    if isinstance(tree, dict):
        for k in tree:
            yield from walk_items(tree[k], prefix + (k,))
    else:
        yield prefix, tree


def unflatten_items(flat: dict) -> dict:
    """Inverse of :func:`walk_items` over a {path tuple: leaf} map;
    keys are emitted in sorted path order (shared by both folds, same
    reasoning as above)."""
    out: dict = {}
    for path in sorted(flat, key=lambda p: tuple(map(str, p))):
        d = out
        for k in path[:-1]:
            d = d.setdefault(k, {})
        d[path[-1]] = flat[path]
    return out


class TreeFold:
    """Streaming weighted FedAvg over dict pytrees: contributions fold
    one at a time into per-path running f32 sums (``_avg_leaves`` op
    for op — contrib ``nan_to_num(leaf.astype(f32)) * w``, running
    add, one divide by the total weight at :meth:`finalize`), so a
    caller never holds more than one contributor's tree plus the
    accumulator.  **Bit-identical** to ``fedavg_trees`` over the same
    contribution order: the float summation sequence is exactly the
    list fold's.  Key-union semantics match too — a path missing from
    some contributors still divides by the TOTAL weight (absent
    contributors dilute, ``src/Utils.py:35-66``).

    This is the streaming shape the round strategies' reference oracle
    (:func:`~split_learning_tpu.runtime.strategies.aggregate_cluster`)
    folds with, so no server round path accumulates a list of full
    per-client parameter trees (slcheck AG001); the runtime's
    :class:`~split_learning_tpu.runtime.aggregate.StreamingFold` is
    the wire-facing twin (numpy/mesh backends, reorder window) proven
    bit-identical against it."""

    def __init__(self):
        self._acc: dict = {}
        self._dtype: dict = {}
        self.total_w: float = 0.0

    def add(self, tree: Any, weight: float = 1.0) -> None:
        self.total_w += float(weight)
        for path, leaf in walk_items(tree):
            t = jnp.nan_to_num(
                jnp.asarray(leaf, dtype=jnp.float32)) * weight
            if path in self._acc:
                self._acc[path] = self._acc[path] + t
            else:
                self._acc[path] = t
                self._dtype[path] = jnp.asarray(leaf).dtype

    def finalize(self) -> dict:
        if not self._acc:
            return {}

        def div(path):
            avg = self._acc[path] / self.total_w
            dt = self._dtype[path]
            return (jnp.round(avg).astype(dt) if _is_int_dtype(dt)
                    else avg.astype(dt))

        return unflatten_items({p: div(p) for p in self._acc})


def fedavg_psum(params: Any, weight: jnp.ndarray, axis_name: str) -> Any:
    """In-mesh weighted FedAvg: each mesh index along ``axis_name`` holds one
    client's params and a scalar sample weight; returns the weighted mean,
    replicated along the axis.

    Preserves the reference's NaN-zeroing and integer-rounding semantics so a
    client whose shard diverged (NaN weights) contributes zeros, diluted by
    its weight, exactly as the host-side fold does.
    """
    total_w = jax.lax.psum(weight, axis_name)

    def avg(leaf):
        orig_dtype = leaf.dtype
        t = jnp.nan_to_num(leaf.astype(jnp.float32)) * weight
        s = jax.lax.psum(t, axis_name) / total_w
        if _is_int_dtype(orig_dtype):
            return jnp.round(s).astype(orig_dtype)
        return s.astype(orig_dtype)

    return jax.tree_util.tree_map(avg, params)
