"""Per-stage auxiliary heads for decoupled (async) split learning.

*Decoupled Split Learning via Auxiliary Loss* (arxiv 2601.19261)
removes the backward wire dependence of split learning: instead of
parking on ``gradient_queue`` until the downstream stage returns a
cotangent, a non-final stage attaches a small local head to its cut
boundary, computes a local classification loss against the batch's
labels (which already ride every Activation frame), and steps
immediately after its forward tick.  The only wire traffic left is the
forward activation stream and the round's Update upload — the gradient
plane (and its EF-sparsifying codec) goes dormant.

Two head architectures, selected by ``learning.aux-head``:

* ``pooled-linear`` — mean-pool every float leaf of the boundary
  pytree over its non-batch, non-feature axes, concatenate along the
  feature axis, one ``Dense(num_classes)``.  The cheapest probe; its
  gradient still reaches every boundary feature.
* ``projection-mlp`` — the same pooling into
  ``Dense(learning.aux-hidden) -> gelu -> Dense(num_classes)``; a
  slightly richer local objective for deep cuts whose pooled features
  are not linearly separable.

The head is built from the *plan's cut shapes*: the client shapes it
lazily from ``jax.eval_shape`` of its shard's forward at the first
batch, so any model/cut combination (including pytree boundaries like
BERT's ``(hidden, mask)``) works without per-model code.  Aux
parameters and their optimizer state are CLIENT-LOCAL — they never
ride Update frames (the server folds shard weights only) and they
reset whenever a re-plan moves the cut (the boundary shape changed, so
the old head is another tensor's probe).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

#: classes per dataset — mirrors runtime/plan.DATASET_CLASSES without
#: importing the (heavier) planning module from the ops layer
_DATASET_CLASSES = {
    "CIFAR10": 10, "CIFAR100": 100, "MNIST": 10,
    "AGNEWS": 4, "EMOTION": 6, "SPEECHCOMMANDS": 10,
}


def num_classes_for(model_key: str) -> int:
    """Label-space size for a ``{MODEL}_{DATASET}`` registry key.

    Raises for a dataset without a known classification label space
    (e.g. token-modelling datasets whose "labels" are token ids): a
    silently-defaulted head would feed out-of-range labels to the aux
    cross-entropy and train every non-final stage toward garbage —
    async mode NEEDS a classification label space (README "when NOT
    to use it")."""
    dataset = model_key.split("_", 1)[1] if "_" in model_key else ""
    try:
        return _DATASET_CLASSES[dataset]
    except KeyError:
        raise ValueError(
            f"learning.mode: async needs a classification label space, "
            f"but dataset {dataset!r} (model key {model_key!r}) has no "
            "registered class count — stay on sync for this workload "
            "or register it in ops/auxiliary._DATASET_CLASSES") from None


def _pool(a: jnp.ndarray) -> jnp.ndarray:
    """(B, ...) -> (B, F): mean over every axis between batch and
    feature.  1-D leaves become a (B, 1) column so scalars-per-sample
    still contribute a feature."""
    if a.ndim <= 1:
        return a.reshape(-1, 1)
    if a.ndim == 2:
        return a
    return a.mean(axis=tuple(range(1, a.ndim - 1)))


class AuxHead(nn.Module):
    """Local classification probe on one cut boundary.

    ``hidden == 0`` is the pooled-linear form; ``hidden > 0`` inserts
    the projection MLP.  The input may be any pytree — float leaves are
    pooled and concatenated, non-float leaves (masks, token ids) are
    ignored (no gradient could flow through them anyway)."""
    num_classes: int
    hidden: int = 0

    @nn.compact
    def __call__(self, boundary):
        feats = []
        for leaf in jax.tree_util.tree_leaves(boundary):
            a = jnp.asarray(leaf)
            if not jnp.issubdtype(a.dtype, jnp.floating):
                continue
            feats.append(_pool(a.astype(jnp.float32)))
        if not feats:
            raise ValueError(
                "aux head: boundary pytree has no float leaves to probe")
        x = feats[0] if len(feats) == 1 else jnp.concatenate(feats, -1)
        if self.hidden:
            x = nn.gelu(nn.Dense(self.hidden, name="proj")(x))
        return nn.Dense(self.num_classes, name="probe")(x)


def build_aux_head(kind: str, num_classes: int,
                   hidden: int = 64) -> AuxHead:
    """``learning.aux-head`` -> module (same vocabulary the config
    validates)."""
    if kind == "pooled-linear":
        return AuxHead(num_classes=num_classes, hidden=0)
    if kind == "projection-mlp":
        return AuxHead(num_classes=num_classes, hidden=max(1, hidden))
    raise ValueError(f"unknown aux head kind {kind!r}")


def init_aux_params(head: AuxHead, rng, boundary_shapes) -> dict:
    """Initialize head params from a boundary SHAPE pytree (the
    ``jax.eval_shape`` result of the shard's forward): zeros of the
    right shape/dtype are enough — flax initialization only reads
    shapes."""
    zeros = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), boundary_shapes)
    return head.init(rng, zeros)["params"]


def aux_shapes_signature(boundary_shapes) -> tuple:
    """Hashable (shape, dtype) signature of a boundary shape pytree —
    what the client compares to decide whether a re-plan moved the cut
    (and therefore whether the aux head + its optimizer state must
    reset)."""
    return tuple((tuple(s.shape), str(s.dtype))
                 for s in jax.tree_util.tree_leaves(boundary_shapes))
