"""Fused blockwise (flash) attention: Pallas TPU kernels + blockwise VJP.

The hot op of every transformer in the zoo.  The reference computes
attention as separate matmul + softmax + matmul torch calls
(``/root/reference/src/model/BERT_AGNEWS.py:56-80``); on TPU that
materializes the (S, S) score matrix in HBM.  These kernels stream K/V
blocks through VMEM with the online-softmax accumulator, so the score
matrix never leaves the core: O(S) memory, MXU-shaped (block_q x D) @
(D x block_k) contractions.

* forward: ``pl.pallas_call`` over a (batch*heads, S/block_q) grid;
  K/V blocks iterated inside with ``lax.fori_loop``; causal masking via
  2-D ``broadcasted_iota`` against the grid position.  Also emits the
  per-row logsumexp (FlashAttention-2's L = m + log l) for the backward.
* backward: two Pallas kernels (the standard FA-2 decomposition).
  ``dKV``: grid over K/V blocks, inner loop over Q blocks — each
  instance owns one (block_k, D) dK/dV tile, no atomics.  ``dQ``: grid
  over Q blocks, inner loop over K/V blocks.  Probabilities are
  rebuilt as ``exp(s - lse)`` (no second online pass needed), and
  ``delta = rowsum(dO * O)`` is a cheap XLA-fused pre-pass.
  Causal runs skip fully-masked blocks in both kernels (~2x fewer MXU
  contractions at large S).
* ``interpret=None`` auto-selects the Pallas interpreter off-TPU, so the
  same code path runs in CPU tests and compiles natively on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from split_learning_tpu.ops.kernels.util import (
    pick_block as _pick_block, resolve_interpret,
)

NEG_INF = -1e30


def _pick_precision(dtype):
    """Full-f32 MXU accumulation for genuinely-f32 inputs (the MXU's
    native multiply is bf16; DEFAULT would silently truncate); bf16
    inputs keep the fast single-pass path.  Forward and backward MUST
    agree or gradients desync from the primal."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _dot(a, b, dims, precision):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=precision)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool, scale: float, block_q: int, precision):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, D)
    s_total = k_ref.shape[1]
    nk = s_total // block_k

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    # causal: K/V blocks entirely in this query block's future contribute
    # exactly zero — skip them (~2x fewer MXU contractions at large S)
    nk_eff = jnp.minimum(
        nk, ((qi + 1) * block_q + block_k - 1) // block_k) if causal \
        else nk

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = _dot(q, k, ((1,), (1,)), precision)        # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        acc_new = acc * corr + _dot(p, v, ((1,), (0,)), precision)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # logsumexp of the SCALED scores: exp(s - lse) rebuilds softmax rows
    # exactly in the backward kernels
    lse_ref[0] = (m + jnp.log(l_safe))[:, 0]


def _flash_fwd_bhsd(q, k, v, causal: bool, interpret: bool,
                    block_q: int, block_k: int):
    """(BH, S, D) flattened forward via pallas_call -> (o, lse)."""
    bh, s, d = q.shape
    scale = 1.0 / np.sqrt(d)
    grid = (bh, s // block_q)
    precision = _pick_precision(q.dtype)
    kernel = functools.partial(_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale,
                               block_q=block_q, precision=precision)
    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((bh, s), jnp.float32)],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block_q), lambda b, i: (b, i))],
        interpret=interpret,
    )(q, k, v)


# --------------------------------------------------------------------------
# backward (FA-2 decomposition: dKV over K-blocks, dQ over Q-blocks)
# --------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q: int, block_k: int,
                    causal: bool, scale: float, precision):
    kb = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                   # (block_k, D)
    v = v_ref[0].astype(jnp.float32)
    s_total = q_ref.shape[1]
    nq = s_total // block_q

    # causal: Q blocks entirely before this K block see none of it
    qb_start = (kb * block_k) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q)]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q)]
        s = _dot(q, k, ((1,), (1,)), precision) * scale  # (bq, bk)
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                  # exact softmax rows
        dv_new = dv + _dot(p, do, ((0,), (0,)), precision)
        dp = _dot(do, v, ((1,), (1,)), precision)      # (bq, bk)
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk + _dot(ds, q, ((0,), (0,)), precision)
        return dk_new, dv_new

    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dk, dv = jax.lax.fori_loop(qb_start, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, block_q: int, block_k: int, causal: bool,
                   scale: float, precision):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                   # (block_q, D)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    s_total = k_ref.shape[1]
    nk = s_total // block_k
    nk_eff = jnp.minimum(
        nk, ((qi + 1) * block_q + block_k - 1) // block_k) if causal \
        else nk

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = _dot(q, k, ((1,), (1,)), precision) * scale  # (bq, bk)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = _dot(do, v, ((1,), (1,)), precision)
        ds = p * (dp - delta[:, None]) * scale
        return dq + _dot(ds, k, ((1,), (0,)), precision)

    dq = jax.lax.fori_loop(0, nk_eff, body,
                           jnp.zeros(q.shape, jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, interpret, block_q, block_k):
    o, _ = _flash_fwd_bhsd(q, k, v, causal, interpret, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, causal, interpret, block_q, block_k):
    o, lse = _flash_fwd_bhsd(q, k, v, causal, interpret, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, interpret, block_q, block_k, res, do):
    q, k, v, o, lse = res
    bh, s, d = q.shape
    scale = 1.0 / np.sqrt(d)
    precision = _pick_precision(q.dtype)
    # delta = rowsum(dO * O): cheap elementwise pre-pass, XLA fuses it
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(axis=-1)

    full = pl.BlockSpec((1, s, d), lambda b, j: (b, 0, 0))
    row_full = pl.BlockSpec((1, s), lambda b, j: (b, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          precision=precision),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        grid=(bh, s // block_k),
        in_specs=[full,
                  pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                  pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                  full, row_full, row_full],
        out_specs=[pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                   pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0))],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          precision=precision),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, s // block_q),
        in_specs=[pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                  full, full,
                  pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
                  pl.BlockSpec((1, block_q), lambda b, i: (b, i))],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = False,
                    interpret: bool | None = None,
                    block_q: int = 128, block_k: int = 128) -> jnp.ndarray:
    """Fused attention over (B, S, H, D) tensors.

    ``interpret=None`` runs the Pallas interpreter unless on real TPU.
    S must be divisible by the (auto-shrunk) block sizes.
    """
    interpret = resolve_interpret(interpret)
    b, s, h, d = q.shape
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(s, block_k)
    to_bhsd = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)  # noqa
    out = _flash(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal, interpret,
                 block_q, block_k)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
