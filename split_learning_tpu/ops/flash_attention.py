"""Fused blockwise (flash) attention: Pallas TPU kernel + blockwise VJP.

The hot op of every transformer in the zoo.  The reference computes
attention as separate matmul + softmax + matmul torch calls
(``/root/reference/src/model/BERT_AGNEWS.py:56-80``); on TPU that
materializes the (S, S) score matrix in HBM.  This kernel streams K/V
blocks through VMEM with the online-softmax accumulator, so the score
matrix never leaves the core: O(S) memory, MXU-shaped (block_q x D) @
(D x block_k) contractions.

* forward: ``pl.pallas_call`` over a (batch*heads, S/block_q) grid;
  K/V blocks iterated inside with ``lax.fori_loop``; causal masking via
  2-D ``broadcasted_iota`` against the grid position.
* backward: standard flash-attention recompute formulas
  (dV = P^T dO, dS = P * (dP - rowsum(dO*O)), dQ/dK from dS) evaluated
  blockwise under ``lax.scan`` — O(S) memory, XLA-fused; a dedicated
  Pallas backward kernel can swap in behind the same ``custom_vjp``.
* ``interpret=None`` auto-selects the Pallas interpreter off-TPU, so the
  same code path runs in CPU tests and compiles natively on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _pick_precision(dtype):
    """Full-f32 MXU accumulation for genuinely-f32 inputs (the MXU's
    native multiply is bf16; DEFAULT would silently truncate); bf16
    inputs keep the fast single-pass path.  Forward and backward MUST
    agree or gradients desync from the primal."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _pick_block(s: int, target: int = 128) -> int:
    """Largest divisor of s that is <= target (TPU-friendly when s is a
    multiple of 128; exact fallback for small/odd test shapes)."""
    b = min(s, target)
    while s % b:
        b -= 1
    return b


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                scale: float, block_q: int, precision):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, D)
    s_total = k_ref.shape[1]
    nk = s_total // block_k

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    # causal: K/V blocks entirely in this query block's future contribute
    # exactly zero — skip them (~2x fewer MXU contractions at large S)
    nk_eff = jnp.minimum(
        nk, ((qi + 1) * block_q + block_k - 1) // block_k) if causal \
        else nk

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision)                       # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.where(l > 0, l, 1.0)).astype(o_ref.dtype)


def _flash_fwd_bhsd(q, k, v, causal: bool, interpret: bool,
                    block_q: int, block_k: int):
    """(BH, S, D) flattened forward via pallas_call."""
    bh, s, d = q.shape
    scale = 1.0 / np.sqrt(d)
    grid = (bh, s // block_q)
    precision = _pick_precision(q.dtype)
    kernel = functools.partial(_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale,
                               block_q=block_q, precision=precision)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, interpret, block_q, block_k):
    return _flash_fwd_bhsd(q, k, v, causal, interpret, block_q, block_k)


def _flash_fwd_rule(q, k, v, causal, interpret, block_q, block_k):
    o = _flash(q, k, v, causal, interpret, block_q, block_k)
    return o, (q, k, v, o)


def _flash_bwd_rule(causal, interpret, block_q, block_k, res, do):
    """Blockwise flash backward (recompute P per K-block under scan)."""
    q, k, v, o = res
    bh, s, d = q.shape
    scale = 1.0 / np.sqrt(d)
    prec = _pick_precision(q.dtype)
    q32, k32, v32 = (t.astype(jnp.float32) for t in (q, k, v))
    do32, o32 = do.astype(jnp.float32), o.astype(jnp.float32)

    # row softmax stats, blockwise over k
    nk = s // block_k

    def stat_body(carry, kb):
        m, l = carry
        kblk = jax.lax.dynamic_slice_in_dim(k32, kb * block_k, block_k, 1)
        sblk = jax.lax.dot_general(
            q32, kblk, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32, precision=prec) * scale
        if causal:
            q_pos = jnp.arange(s)[:, None]
            k_pos = kb * block_k + jnp.arange(block_k)[None, :]
            sblk = jnp.where((k_pos <= q_pos)[None], sblk, NEG_INF)
        m_new = jnp.maximum(m, sblk.max(axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.exp(
            sblk - m_new[..., None]).sum(axis=-1)
        return (m_new, l), None

    (m, l), _ = jax.lax.scan(
        stat_body, (jnp.full((bh, s), NEG_INF, jnp.float32),
                    jnp.zeros((bh, s), jnp.float32)), jnp.arange(nk))
    l = jnp.where(l > 0, l, 1.0)
    delta = (do32 * o32).sum(axis=-1)                  # (BH, S)

    def grad_body(dq, kb):
        kblk = jax.lax.dynamic_slice_in_dim(k32, kb * block_k, block_k, 1)
        vblk = jax.lax.dynamic_slice_in_dim(v32, kb * block_k, block_k, 1)
        sblk = jax.lax.dot_general(
            q32, kblk, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32, precision=prec) * scale
        if causal:
            q_pos = jnp.arange(s)[:, None]
            k_pos = kb * block_k + jnp.arange(block_k)[None, :]
            sblk = jnp.where((k_pos <= q_pos)[None], sblk, NEG_INF)
        p = jnp.exp(sblk - m[..., None]) / l[..., None]  # (BH, S, bk)
        dv = jax.lax.dot_general(p, do32, (((1,), (1,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
        dp = jax.lax.dot_general(do32, vblk, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jax.lax.dot_general(
            ds, kblk, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32, precision=prec)
        dk = jax.lax.dot_general(ds, q32, (((1,), (1,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
        return dq, (dk, dv)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        grad_body, jnp.zeros_like(q32), jnp.arange(nk))
    # scan stacks per-block (BH, block_k, D) grads -> reorder to (BH, S, D)
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(bh, s, d)
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(bh, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = False,
                    interpret: bool | None = None,
                    block_q: int = 128, block_k: int = 128) -> jnp.ndarray:
    """Fused attention over (B, S, H, D) tensors.

    ``interpret=None`` runs the Pallas interpreter unless on real TPU.
    S must be divisible by the (auto-shrunk) block sizes.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = q.shape
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(s, block_k)
    to_bhsd = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)  # noqa
    out = _flash(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal, interpret,
                 block_q, block_k)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
