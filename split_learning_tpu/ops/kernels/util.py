"""Shared helpers for the Pallas kernel plane.

One copy of the two decisions every kernel call site makes (the flash
attention fwd/bwd kernels made them privately before this package
existed):

* :func:`pick_block` — grid block sizing: the largest divisor of the
  gridded extent that fits the requested target, so TPU-friendly shapes
  get full 128-wide blocks and small/odd test shapes still divide
  exactly;
* :func:`resolve_interpret` — the ``interpret=None`` auto-select: the
  Pallas interpreter off-TPU (CPU tests run the SAME kernel code), the
  native Mosaic lowering on real TPU.
"""

from __future__ import annotations


def pick_block(s: int, target: int = 128) -> int:
    """Largest divisor of s that is <= target (TPU-friendly when s is a
    multiple of 128; exact fallback for small/odd test shapes)."""
    b = min(s, target)
    while s % b:
        b -= 1
    return b


def pick_pair_block(t: int, tile: int, target: int = 128) -> int:
    """Largest divisor b of t with b <= target AND b * tile even — the
    int4 packer consumes code PAIRS, so every grid instance must own an
    even number of codes.  The quantizer's padding guarantees t * tile
    is even, so a valid b always exists (b = 2 when tile is odd)."""
    if t * tile % 2:
        raise ValueError(
            f"t*tile must be even for int4 packing, got {t}x{tile}")
    b = min(t, target)
    while t % b or (b * tile) % 2:
        b -= 1
    return b


def resolve_interpret(interpret: bool | None) -> bool:
    """``interpret=None`` runs the Pallas interpreter unless on real
    TPU, so the same kernel code path serves CPU tests and compiles
    natively on TPU."""
    if interpret is None:
        import jax
        return jax.default_backend() != "tpu"
    return interpret
