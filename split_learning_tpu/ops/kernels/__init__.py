"""Pallas hot-path kernel plane (ROADMAP item 1, second half).

Single-pass fused kernels for the two remaining named hot blocks that
were plain XLA op chains:

* :mod:`~split_learning_tpu.ops.kernels.quant` — fused tiled absmax
  quantize (absmax reduce, scale, round/clip, NaN-scale sentinel, int4
  nibble-pack) and its dequantize mirror, one VMEM-resident pass per
  leaf instead of the ~8-op XLA chain's repeated HBM round-trips;
* :mod:`~split_learning_tpu.ops.kernels.update` — the fused
  round-boundary stage update (FedAvg divide + FedAvgM momentum + wire
  dtype cast) as one pass over each stage leaf.

All kernels follow ``ops/flash_attention.py``'s ``interpret=None``
auto-select (:func:`~.util.resolve_interpret`): the SAME kernel code
runs under the Pallas interpreter in CPU tests and lowers natively on
TPU.  Every call site keeps the pre-existing jitted XLA chain as the
parity oracle — kernels are bit-identical for int8 codec + update on
CPU, tolerance-pinned for int4 rounding edges — and the slcheck
``pallas`` analyzer (PK001) asserts an ENABLED kernel's ``pallas_call``
actually appears in the traced hot-path jaxpr, so a refactor cannot
silently fall back to XLA while the config claims kernels are on.

Gating: the ``kernels:`` config block becomes a :class:`KernelPlan`.
The plan travels two ways — explicitly (``QuantCodec(...,
kernels=...)``, ``MeshFoldBackend(kernels=...)``) or through the
process-wide default installed by :func:`configure` (which
``make_codecs``/``make_fold_backend`` call with the loaded config, so
the self-describing receiver decode path — which has no config in
scope — follows the same plan).  Default: everything off; behavior is
byte-for-byte the pre-kernel XLA path.
"""

from __future__ import annotations

import contextlib
import dataclasses

from split_learning_tpu.ops.kernels.util import (  # noqa: F401
    pick_block, pick_pair_block, resolve_interpret,
)

__all__ = ["KernelPlan", "DISABLED", "as_plan", "configure", "plan",
           "override", "pick_block", "pick_pair_block",
           "resolve_interpret"]


@dataclasses.dataclass(frozen=True)
class KernelPlan:
    """Which Pallas kernels are live, and their grid block target."""
    quantize: bool = False
    dequantize: bool = False
    stage_update: bool = False
    block: int = 128

    @property
    def any(self) -> bool:
        return self.quantize or self.dequantize or self.stage_update


DISABLED = KernelPlan()
_active: KernelPlan = DISABLED


def as_plan(obj) -> KernelPlan:
    """Coerce a config ``kernels:`` section (or a plan, or None) into a
    :class:`KernelPlan`.  None means "no opinion": the process-wide
    plan — so partial config shims (e.g. the scheduler's codec-retune
    shim) never silently disable configured kernels."""
    if obj is None:
        return _active
    if isinstance(obj, KernelPlan):
        return obj
    return KernelPlan(
        quantize=bool(getattr(obj, "quantize", False)),
        dequantize=bool(getattr(obj, "dequantize", False)),
        stage_update=bool(getattr(obj, "stage_update", False)),
        block=int(getattr(obj, "block", 128)))


def configure(obj) -> KernelPlan:
    """Install the process-wide kernel plan from a loaded config's
    ``kernels`` section.  ``configure(None)`` is a no-op returning the
    current plan."""
    global _active
    if obj is not None:
        _active = as_plan(obj)
    return _active


def plan() -> KernelPlan:
    """The process-wide kernel plan (default: :data:`DISABLED`)."""
    return _active


@contextlib.contextmanager
def override(**fields):
    """Test helper: temporarily replace fields of the process plan."""
    global _active
    prev = _active
    _active = dataclasses.replace(prev, **fields)
    try:
        yield _active
    finally:
        _active = prev
