"""Fused tiled-absmax quantize / dequantize Pallas kernels.

The XLA chain in ``runtime/codec/quant.py`` (``_quantize_dev``) is ~8
separate ops — abs, tile max, scale select, divide, round, clip, NaN
mask, int cast, and for int4 a strided-gather nibble pack — each a full
HBM round-trip over the leaf.  These kernels do the whole thing in one
VMEM-resident pass per block of tiles: a grid instance loads ``(block,
tile)`` floats once and emits the int codes (nibble-packed for int4)
plus the per-tile scales.

Numerics are the oracle's, op for op: ``scale = amax/qmax`` (qmax 127
int8 / 7 int4), all-zero tile -> scale 1, NON-FINITE tile -> NaN scale
sentinel with zeroed codes, int4 codes packed two's-complement lo
nibble first.  int8 output is bit-identical to the XLA chain on CPU
(the parity tests pin it); int4 shares the same rounding, packed
identically.

Layout: the caller hands the ALREADY padded+tiled ``(T, tile)`` f32
array (padding is a cheap XLA prologue — the expensive multi-pass math
is what moves into the kernel).  Codes/scales come back flat, exactly
the shapes ``_quantize_dev`` produced.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from split_learning_tpu.ops.kernels.util import (
    pick_block, pick_pair_block, resolve_interpret,
)


def _quantize_kernel(t_ref, q_ref, s_ref, *, qmax: float, pack: bool):
    t = t_ref[...].astype(jnp.float32)            # (block, tile)
    amax = jnp.max(jnp.abs(t), axis=1)
    scale = jnp.where(jnp.isfinite(amax),
                      jnp.where(amax > 0, amax / qmax, 1.0),
                      jnp.nan).astype(jnp.float32)
    codes = jnp.clip(jnp.round(t / scale[:, None]), -qmax, qmax)
    # NaN codes (non-finite tile: scale is NaN) become 0 — the NaN
    # scale alone carries the divergence (oracle semantics)
    codes = jnp.where(jnp.isfinite(codes), codes, 0.0).astype(jnp.int8)
    if pack:
        u = codes.reshape(-1).astype(jnp.uint8) & 0xF
        pairs = u.reshape(-1, 2)                  # lo nibble first
        q_ref[0, :] = (pairs[:, 0] | (pairs[:, 1] << 4)).astype(
            jnp.uint8)
    else:
        q_ref[...] = codes
    s_ref[0, :] = scale


def quantize_tiles(tiles, *, bits: int, block: int = 128,
                   interpret: bool | None = None):
    """One-pass (codes, scales) for a padded ``(T, tile)`` f32 array.

    Returns the flat code array (int8 for bits=8; nibble-packed uint8,
    half the length, for bits=4) and the ``(T,)`` f32 scale vector —
    the exact shapes/values of the ``_quantize_dev`` XLA chain.
    """
    interpret = resolve_interpret(interpret)
    t_count, tile = tiles.shape
    qmax = 127.0 if bits == 8 else 7.0
    if bits == 4:
        b = pick_pair_block(t_count, tile, block)
    else:
        b = pick_block(t_count, block)
    nb = t_count // b
    if bits == 4:
        q_shape = jax.ShapeDtypeStruct((nb, b * tile // 2), jnp.uint8)
        q_spec = pl.BlockSpec((1, b * tile // 2), lambda i: (i, 0))
    else:
        q_shape = jax.ShapeDtypeStruct((t_count, tile), jnp.int8)
        q_spec = pl.BlockSpec((b, tile), lambda i: (i, 0))
    q, s = pl.pallas_call(
        functools.partial(_quantize_kernel, qmax=qmax,
                          pack=(bits == 4)),
        out_shape=[q_shape,
                   jax.ShapeDtypeStruct((nb, b), jnp.float32)],
        grid=(nb,),
        in_specs=[pl.BlockSpec((b, tile), lambda i: (i, 0))],
        out_specs=[q_spec, pl.BlockSpec((1, b), lambda i: (i, 0))],
        interpret=interpret,
    )(tiles)
    return q.reshape(-1), s.reshape(-1)


def _dequantize_kernel(q_ref, s_ref, o_ref, *, pack: bool, tile: int):
    if pack:
        u = q_ref[0, :].astype(jnp.uint8)         # (block*tile//2,)
        lo, hi = u & 0xF, u >> 4
        codes = jnp.stack([lo, hi], axis=-1).reshape(-1, tile)
        codes = jnp.where(codes < 8, codes,
                          codes.astype(jnp.int32) - 16)
    else:
        codes = q_ref[...]                        # (block, tile)
    scale = s_ref[0, :]                           # (block,)
    o_ref[...] = codes.astype(jnp.float32) * scale[:, None]


def dequantize_tiles(q, scale, *, tile: int, bits: int,
                     block: int = 128,
                     interpret: bool | None = None):
    """Mirror pass: flat codes + ``(T,)`` scales -> flat ``(T*tile,)``
    f32 (the caller slices off the padding and reshapes)."""
    interpret = resolve_interpret(interpret)
    t_count = scale.shape[0]
    if bits == 4:
        b = pick_pair_block(t_count, tile, block)
        nb = t_count // b
        q_in = q.reshape(nb, b * tile // 2)
        q_spec = pl.BlockSpec((1, b * tile // 2), lambda i: (i, 0))
    else:
        b = pick_block(t_count, block)
        nb = t_count // b
        q_in = q.reshape(t_count, tile)
        q_spec = pl.BlockSpec((b, tile), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_dequantize_kernel, pack=(bits == 4),
                          tile=tile),
        out_shape=jax.ShapeDtypeStruct((t_count, tile), jnp.float32),
        grid=(nb,),
        in_specs=[q_spec,
                  pl.BlockSpec((1, b), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((b, tile), lambda i: (i, 0)),
        interpret=interpret,
    )(q_in, scale.reshape(nb, b))
    return out.reshape(-1)
