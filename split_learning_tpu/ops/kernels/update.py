"""Fused round-boundary stage-update Pallas kernels.

``MeshFoldBackend._fused_update`` finishes a stage in one jitted
program, but inside that program each leaf is still an XLA chain —
divide, (momentum multiply-add, subtract,) cast — i.e. several HBM
round-trips over every full-stage buffer at every round boundary.
These kernels collapse each leaf's finish into one VMEM-resident pass:

* :func:`finalize_leaf` — FedAvg divide (+ round for int leaves) +
  wire-dtype cast;
* :func:`momentum_leaf` — the FedAvgM step
  ``v' = m*v + (base - acc/tw); p' = (base - v').astype(wire_dtype)``
  emitting both the new params and the carried velocity in one pass.

The op order inside the kernel matches the jnp oracle exactly, so mesh
and host folds stay bit-identical on CPU (the 2-round velocity-carry
parity test pins it).  Leaves are viewed as ``(d0, rest)`` — axis 0
preserved — and the grid blocks along axis 0, composing with the
ZeRO-style leaf-axis-0 ``agg`` sharding the backend applies.  The
jit/donation wrapper stays in ``runtime/aggregate.py`` (JX007 audits
it there); these are pure per-leaf ops traced into that program.

Scalars (total weight, momentum) arrive as traced values and ride in
as (1, 1) blocks broadcast to every grid instance — a new total weight
does NOT recompile the program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from split_learning_tpu.ops.kernels.util import (
    pick_block, resolve_interpret,
)


def kernel_ok(leaf) -> bool:
    """Kernel-eligible: at least 1-D and non-empty (0-d/empty leaves
    fall back to the XLA chain — no grid to block)."""
    return getattr(leaf, "ndim", 0) >= 1 and getattr(leaf, "size", 0) > 0


def _rows(x):
    """Leaf -> (d0, rest) view: axis 0 (the ``agg`` shard axis) kept,
    the rest flattened."""
    return x.reshape(x.shape[0], -1)


def _finalize_kernel(acc_ref, tw_ref, out_ref, *, rnd: bool):
    a32 = acc_ref[...] / tw_ref[0, 0]
    if rnd:
        a32 = jnp.round(a32)
    out_ref[...] = a32.astype(out_ref.dtype)


def finalize_leaf(acc, tw, dtype, *, rnd: bool = False,
                  block: int = 128, interpret: bool | None = None):
    """``(acc / tw)`` (+ round for int wire dtypes) cast to ``dtype``,
    one pass."""
    interpret = resolve_interpret(interpret)
    x = _rows(acc)
    d0, rest = x.shape
    b = pick_block(d0, block)
    tw2 = jnp.reshape(tw, (1, 1)).astype(jnp.float32)
    out = pl.pallas_call(
        functools.partial(_finalize_kernel, rnd=rnd),
        out_shape=jax.ShapeDtypeStruct((d0, rest), dtype),
        grid=(d0 // b,),
        in_specs=[pl.BlockSpec((b, rest), lambda i: (i, 0)),
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((b, rest), lambda i: (i, 0)),
        interpret=interpret,
    )(x, tw2)
    return out.reshape(acc.shape)


def _momentum_kernel(acc_ref, base_ref, vel_ref, tw_ref, m_ref,
                     p_ref, nv_ref):
    a32 = acc_ref[...] / tw_ref[0, 0]
    nv = m_ref[0, 0] * vel_ref[...] + (base_ref[...] - a32)
    nv_ref[...] = nv
    p_ref[...] = (base_ref[...] - nv).astype(p_ref.dtype)


def momentum_leaf(acc, base, vel, tw, m, dtype, *, block: int = 128,
                  interpret: bool | None = None):
    """FedAvgM finish for one leaf: returns ``(params.astype(dtype),
    new_velocity f32)`` in one pass, oracle op order."""
    interpret = resolve_interpret(interpret)
    x = _rows(acc)
    d0, rest = x.shape
    b = pick_block(d0, block)
    leaf2 = pl.BlockSpec((b, rest), lambda i: (i, 0))
    scalar = pl.BlockSpec((1, 1), lambda i: (0, 0))
    tw2 = jnp.reshape(tw, (1, 1)).astype(jnp.float32)
    m2 = jnp.reshape(m, (1, 1)).astype(jnp.float32)
    p, nv = pl.pallas_call(
        _momentum_kernel,
        out_shape=[jax.ShapeDtypeStruct((d0, rest), dtype),
                   jax.ShapeDtypeStruct((d0, rest), jnp.float32)],
        grid=(d0 // b,),
        in_specs=[leaf2, leaf2, leaf2, scalar, scalar],
        out_specs=[leaf2, leaf2],
        interpret=interpret,
    )(x, _rows(base), _rows(vel), tw2, m2)
    return p.reshape(acc.shape), nv.reshape(acc.shape)
