"""Per-participant compute performance-attribution plane.

PRs 5 and 7 made the wire and the fleet observable; the compute side
stayed dark: ``train`` is one opaque block in the critical path, the
bench's MFU row has no runtime twin, and nothing accounts for compile
time, retraces, or HBM watermarks while a round runs.  This module is
the compute half of the compute/wire ratio the closed-loop scheduler
(ROADMAP item 1) must consume:

* :class:`SampledStepTimer` — sampled per-stage step timing.  Every hot-loop
  step records its *dispatch* wall (the async-dispatch cost the
  training thread actually pays); every ``perf.sample-every``-th step
  additionally fences the step's outputs (``jax.block_until_ready``
  behind the sampler gate — the ``perf`` slcheck analyzer holds hot
  loops to exactly this discipline) and records the *device* wall, so
  the hot loop stays sync-free in steady state while device time is
  still measured.  A *host* accumulator times data loading/conversion.
  Components feed the existing :class:`~split_learning_tpu.runtime
  .trace.HistogramSet` (``step_dispatch``/``step_device``) and the
  ``step_seconds`` gauge.
* :class:`CompileWatch` — wraps jitted entry points (a
  :class:`~split_learning_tpu.runtime.client.ShardRunner`'s five ops).
  A growth of the wrapped function's jit cache is a compile: counted
  per op, its wall-clock accumulated (``compile_seconds_total``),
  emitted as a ``compile`` span into the span journal (so
  ``tools/sl_trace.py`` critical paths separate compile from compute),
  and — the live twin of slcheck's static JX004 retrace rule — any
  compile after round 0 raises the ``retraces`` fault counter.  The
  compiled step's XLA ``cost_analysis()`` FLOPs are captured once per
  signature, so every later call accrues measured FLOPs for MFU.
* :class:`MemoryWatch` — per-round peak-HBM watermark from
  ``device.memory_stats()`` (falling back to summing
  ``jax.live_arrays()`` where the backend reports none, e.g. CPU),
  published as the ``hbm_peak_bytes`` gauge and compared against a
  static plan estimate (bench.py's memory plan) when one is noted.
* **MFU accounting** — measured FLOPs (CompileWatch) ÷ round wall ÷ a
  per-platform datasheet bf16 peak (:data:`DATASHEET_BF16_TFLOPS`,
  overridable via ``perf.datasheet``; CPU has no datasheet row — the
  bench's measured matmul roofline or a config override stands in).
  Published as the ``mfu`` gauge, piggybacked on HEARTBEAT snapshots
  (gauges ride every :class:`~split_learning_tpu.runtime.telemetry
  .TelemetrySnapshot`), rendered as ``sl_mfu`` on ``/metrics``, and
  written into ``kind=perf`` metrics records.
* :class:`ProfileCapture` — the on-demand ``jax.profiler`` hook:
  ``POST /profile?steps=K`` on the TelemetryExporter arms a K-step
  trace window opened at the next round boundary, artifact landing in
  ``artifacts/runs/<run_id>/profile/round<r>/``.
* :class:`PerfPlane` — the facade a participant owns: round lifecycle
  (``start_round`` / ``note_step`` / ``host`` / ``end_round``), the
  ``kind=perf`` attribution record whose
  ``compute + compile + dispatch + host + wait`` components sum to the
  round's wall by construction, and the gauge updates.

No jax at module import (lazy inside methods): ``tools/sl_perf.py``
and the bench orchestrator read the datasheet table and record schema
without touching an accelerator runtime.
"""

from __future__ import annotations

import contextlib
import json
import pathlib
import threading
import time
import weakref
from typing import Any, Callable

#: kind=perf record schema version (bump on breaking change)
PERF_SCHEMA_VERSION = 1

#: Datasheet bf16 peak TFLOP/s per chip, keyed by jax ``device_kind``
#: (public TPU spec tables; bench.py's MFU section reads this same
#: table).  CPU has no datasheet row: the measured matmul roofline
#: (bench.py) or a ``perf.datasheet`` override stands in.
DATASHEET_BF16_TFLOPS = {
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 459.0,  # v5p
    "TPU v5p": 459.0,
    "TPU v4": 275.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def resolve_peak_tflops(device_kind: str,
                        override: dict | None = None) -> float | None:
    """Datasheet bf16 peak for ``device_kind``; an override mapping
    (``perf.datasheet``) wins — that is also how a CPU proxy run pins
    its measured roofline as the MFU denominator."""
    if override:
        v = override.get(device_kind)
        if v is not None:
            try:
                return float(v)
            except (TypeError, ValueError):
                return None
    return DATASHEET_BF16_TFLOPS.get(device_kind)


def flops_of_compiled(fn, *args, **kwargs) -> float | None:
    """Per-call FLOPs from XLA ``cost_analysis()`` of ``fn`` compiled
    for these arguments (compile-cache hit when the caller already
    executed the same signature); None when the backend reports none."""
    try:
        compiled = fn.lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):   # jax < 0.5 spelling
            cost = cost[0] if cost else {}
        flops = (cost or {}).get("flops")
        return float(flops) if flops else None
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


# --------------------------------------------------------------------------
# sampled step timing
# --------------------------------------------------------------------------

class SampledStepTimer:
    """Sampled per-step timing: dispatch every step, device on sampled
    steps only, host-data via a context manager.

    The hot loop pays ``note_step(t0, tree)`` per step: dispatch wall
    (``now - t0``) always, and — every ``sample_every``-th step — a
    ``block_until_ready`` fence on ``tree`` to measure device wall.
    The device total for the round is *estimated* by scaling the
    sampled mean to the full step count; ``attribution()`` reports the
    raw sampled seconds too so the extrapolation is auditable."""

    def __init__(self, sample_every: int = 16, hists=None, gauges=None,
                 fence: Callable | None = None,
                 compile_overlap: Callable[[float, float], float]
                 | None = None):
        self.sample_every = max(1, int(sample_every))
        self._hists = hists
        self._gauges = gauges
        self._fence = fence
        # compile-time deduplication: a step whose jitted call COMPILED
        # spent most of its window in XLA, and that wall belongs to the
        # `compile` component, not `dispatch` — the CompileWatch hands
        # back the compile seconds overlapping a step window
        self._compile_overlap = compile_overlap
        self._lock = threading.Lock()
        self.round_idx: int | None = None
        self._reset()

    def _reset(self) -> None:
        self.steps = 0
        self.sampled_steps = 0
        self.dispatch_s = 0.0
        self.device_sampled_s = 0.0
        self.host_s = 0.0
        self.samples = 0
        self._t_round = None

    def start_round(self, round_idx: int) -> None:
        with self._lock:
            self._reset()
            self.round_idx = round_idx
            self._t_round = time.perf_counter()

    def note_step(self, t0: float, tree=None, n: int = 0) -> None:
        """One hot-loop step that began at ``perf_counter()`` time
        ``t0``; ``tree`` is the step's output pytree (fenced only on
        sampled steps), ``n`` the samples it trained."""
        t1 = time.perf_counter()
        dispatch = max(0.0, t1 - t0)
        if self._compile_overlap is not None:
            dispatch = max(0.0, dispatch - self._compile_overlap(t0, t1))
        with self._lock:
            self.steps += 1
            self.dispatch_s += dispatch
            self.samples += n
            sampled = tree is not None and \
                self.steps % self.sample_every == 0
        if self._hists is not None:
            self._hists.observe("step_dispatch", dispatch)
        if sampled:
            # the sampler gate: the ONLY device sync the hot loop pays,
            # once every sample-every steps (the ``perf`` slcheck
            # analyzer, PF001, holds every hot-loop fence to this)
            if self._fence is not None:
                self._fence(tree)
            else:
                import jax
                jax.block_until_ready(tree)
            device = max(0.0, time.perf_counter() - t1)
            with self._lock:
                self.sampled_steps += 1
                self.device_sampled_s += device
            if self._hists is not None:
                self._hists.observe("step_device", dispatch + device)
            if self._gauges is not None:
                self._gauges.set("step_seconds",
                                 round(dispatch + device, 6))

    @contextlib.contextmanager
    def host(self):
        """Time a host-data interval (loader fetch, np->device
        conversion) into the ``host`` attribution component."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = max(0.0, time.perf_counter() - t0)
            with self._lock:
                self.host_s += dt

    def device_est_s(self) -> float:
        """Round device-seconds estimate: sampled mean x step count."""
        with self._lock:
            if self.sampled_steps == 0:
                return 0.0
            return (self.device_sampled_s / self.sampled_steps
                    * self.steps)

    def attribution(self, wall_s: float | None = None) -> dict:
        with self._lock:
            wall = (wall_s if wall_s is not None
                    else (time.perf_counter() - self._t_round
                          if self._t_round is not None else 0.0))
            out = {
                "steps": self.steps,
                "sampled_steps": self.sampled_steps,
                "sample_every": self.sample_every,
                "dispatch_s": round(self.dispatch_s, 6),
                "device_sampled_s": round(self.device_sampled_s, 6),
                "host_s": round(self.host_s, 6),
                "wall_s": round(wall, 6),
            }
        out["device_est_s"] = round(self.device_est_s(), 6)
        return out


# --------------------------------------------------------------------------
# compile / retrace accounting
# --------------------------------------------------------------------------

#: per-inner-fn high-water mark of BOOKED jit-cache sizes.  In-process
#: clients with identical (model, layers, learning) share one jitted
#: fn via client.py's ``_OPS_CACHE`` but wrap it with their OWN
#: CompileWatch; when a new signature compiles, every concurrently
#: blocked caller observes the same cache growth — exactly one of
#: them may book the compile (and a possible retrace), or compile_s
#: double-counts across the fleet.  Weak keys: the ledger must not
#: pin a rebuilt runner's dropped ops.
_CACHE_CLAIMS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_CACHE_CLAIMS_LOCK = threading.Lock()


def _claim_cache_growth(fn, after: int) -> bool:
    """True for exactly one observer of a given cache-size level."""
    try:
        with _CACHE_CLAIMS_LOCK:
            booked = _CACHE_CLAIMS.get(fn, 0)
            if after <= booked:
                return False
            _CACHE_CLAIMS[fn] = after
            return True
    except TypeError:   # not weak-referenceable: book unconditionally
        return True


class CompileWatch:
    """Wrap jitted entry points to count compiles, accumulate compile
    wall-clock, journal ``compile`` spans, capture per-signature FLOPs,
    and raise the ``retraces`` counter on any compile after round 0 —
    the live twin of slcheck's static retrace rule (JX004)."""

    def __init__(self, faults=None, tracer=None, gauges=None, log=None):
        self._faults = faults
        self._tracer = tracer
        self._gauges = gauges
        self._log = log
        self._lock = threading.Lock()
        self.compiles: dict[str, int] = {}
        self.compile_s = 0.0
        self.round_compile_s = 0.0
        self.retraces = 0
        self.round_idx = 0
        #: the first round THIS watch participated in — a client that
        #: joins (or restarts) at round 5 pays its cold compiles there,
        #: and those are warmup, not retraces
        self._first_round: int | None = None
        #: ops that have compiled through the CURRENT wrap generation;
        #: only a RE-compile of a warm op counts as a retrace (a
        #: rebuilt runner's fresh ops reset their entry — see wrap())
        self._warm_ops: set[str] = set()
        self._flops: dict[str, float] = {}   # per-call FLOPs by op name
        self._flops_failed: set[str] = set()  # don't re-lower per call
        self.round_flops = 0.0
        # perf_counter intervals of this round's compiles (bounded),
        # so the SampledStepTimer can subtract compile wall from a step
        # window it overlaps instead of double-counting it as dispatch
        self._round_events: list[tuple[float, float]] = []

    def note_round(self, round_idx: int) -> None:
        with self._lock:
            if self._first_round is None:
                self._first_round = round_idx
            self.round_idx = round_idx
            self.round_flops = 0.0
            self.round_compile_s = 0.0
            self._round_events = []

    def overlap(self, t0: float, t1: float) -> float:
        """Compile seconds overlapping the perf_counter window
        [t0, t1] (fed to SampledStepTimer as ``compile_overlap``)."""
        with self._lock:
            return sum(max(0.0, min(b, t1) - max(a, t0))
                       for a, b in self._round_events)

    @staticmethod
    def _cache_size(fn) -> int | None:
        size = getattr(fn, "_cache_size", None)
        if size is None:
            return None
        try:
            return int(size())
        except Exception:  # noqa: BLE001 — foreign callable
            return None

    def _note_compile(self, name: str, t0_wall: float, t0_pc: float,
                      dt: float) -> None:
        with self._lock:
            self.compiles[name] = self.compiles.get(name, 0) + 1
            self.compile_s += dt
            self.round_compile_s += dt
            if len(self._round_events) < 512:
                self._round_events.append((t0_pc, t0_pc + dt))
            # a retrace is a RE-compile of an op that already compiled
            # through this wrap generation, past the participant's own
            # warmup round — first-time compiles of a client joining
            # (or restarting) mid-run, and of a rebuilt runner's fresh
            # ops, are cold compiles, not leaks
            retrace = (self._first_round is not None
                       and self.round_idx > self._first_round
                       and name in self._warm_ops)
            self._warm_ops.add(name)
            if retrace:
                self.retraces += 1
        if retrace:
            if self._faults is not None:
                self._faults.inc("retraces")
            if self._log is not None:
                self._log.warning(
                    f"retrace of {name!r} at round {self.round_idx} "
                    f"({dt:.2f}s): a post-warmup compile means a shape/"
                    "dtype/hash leaked into trace time")
        if self._tracer is not None:
            self._tracer.record("compile", t0_wall, t0_wall + dt,
                                always=True, op=name,
                                round=self.round_idx)
        if self._gauges is not None:
            with self._lock:
                total = self.compile_s
            self._gauges.set("compile_seconds_total", round(total, 4))

    def _ensure_flops(self, name: str, fn, args, kwargs) -> None:
        """Per-call FLOPs captured on the op's FIRST CALL through this
        watch, not its first observed compile: a client sharing an
        already-warm jit cache (same-process feeders share the runner
        ops bundle) never sees a compile but must still get MFU.  The
        trace+lower wall ``cost_analysis`` pays — real even on a
        compile-cache hit — is booked as compile time and into the
        overlap ledger so the hot-loop step that triggered it doesn't
        misattribute it as dispatch."""
        with self._lock:
            if name in self._flops or name in self._flops_failed:
                return
        t0 = time.perf_counter()
        flops = flops_of_compiled(fn, *args, **kwargs)
        dt = time.perf_counter() - t0
        with self._lock:
            if flops:
                self._flops[name] = flops
            else:
                self._flops_failed.add(name)
            self.compile_s += dt
            self.round_compile_s += dt
            if len(self._round_events) < 512:
                self._round_events.append((t0, t0 + dt))

    def wrap(self, name: str, fn):
        """``fn`` with compile detection; calls accrue round FLOPs."""
        if getattr(fn, "_perf_watch", None) is self:
            return fn   # idempotent (hold STARTs re-wrap the runner)
        with self._lock:
            # a fresh fn under a known name = the runner was rebuilt
            # (hyperparams changed mid-hold): its first compile is
            # warmup again, not a retrace — and its per-call FLOPs
            # must be re-captured (a different shard geometry would
            # otherwise keep accruing the OLD shard's FLOPs into MFU)
            self._warm_ops.discard(name)
            self._flops.pop(name, None)
            self._flops_failed.discard(name)

        def wrapped(*args, **kwargs):
            before = self._cache_size(fn)
            t0_wall = time.time()
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            if before is not None:
                after = self._cache_size(fn)
                if (after is not None and after > before
                        and _claim_cache_growth(fn, after)):
                    self._note_compile(name, t0_wall, t0, dt)
            self._ensure_flops(name, fn, args, kwargs)
            with self._lock:
                self.round_flops += self._flops.get(name, 0.0)
            return out

        wrapped._perf_watch = self
        wrapped._perf_inner = fn
        return wrapped

    def wrap_runner(self, runner) -> None:
        """Wrap a ShardRunner's five jitted ops in place (instance
        attributes only — the shared ``_OPS_CACHE`` bundle is
        untouched)."""
        for name in ("fwd", "bwd", "last_step", "whole_step",
                     "apply_update"):
            fn = getattr(runner, name, None)
            if fn is not None:
                setattr(runner, name, self.wrap(name, fn))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "compiles": dict(self.compiles),
                "compile_s_total": round(self.compile_s, 4),
                "compile_s_round": round(self.round_compile_s, 4),
                "retraces": self.retraces,
                "flops_per_step": dict(self._flops),
                "round_flops": self.round_flops,
            }


# --------------------------------------------------------------------------
# HBM watermarks
# --------------------------------------------------------------------------

class MemoryWatch:
    """Per-round device-memory watermarks vs a static plan estimate."""

    def __init__(self, gauges=None):
        self._gauges = gauges
        self._lock = threading.Lock()
        self.peak_bytes: int | None = None
        self.plan_est_bytes: int | None = None

    def note_plan_estimate(self, nbytes: int) -> None:
        """Record the static residency estimate this run was planned
        against (bench.py's memory plan), so the measured watermark is
        comparable to the planner's promise."""
        with self._lock:
            self.plan_est_bytes = int(nbytes)

    def sample(self) -> int | None:
        """Current peak/live device bytes: ``memory_stats()`` where
        the backend reports them, else the summed ``live_arrays``
        footprint (CPU)."""
        import jax
        total = 0
        got = False
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 — backend-dependent API
                ms = None
            if ms:
                total += int(ms.get("peak_bytes_in_use")
                             or ms.get("bytes_in_use") or 0)
                got = True
        if not got:
            try:
                total = sum(int(a.nbytes) for a in jax.live_arrays())
                got = True
            except Exception:  # noqa: BLE001
                return None
        if not got:
            return None
        with self._lock:
            if self.peak_bytes is None or total > self.peak_bytes:
                self.peak_bytes = total
        if self._gauges is not None:
            self._gauges.set("hbm_peak_bytes", total)
        return total

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = {}
            if self.peak_bytes is not None:
                out["hbm_peak_bytes"] = self.peak_bytes
            if self.plan_est_bytes:
                out["hbm_plan_est_bytes"] = self.plan_est_bytes
                if self.peak_bytes:
                    out["hbm_peak_vs_plan"] = round(
                        self.peak_bytes / self.plan_est_bytes, 4)
            return out


# --------------------------------------------------------------------------
# on-demand profiler capture
# --------------------------------------------------------------------------

#: the process-wide capture hot loops tick (see register_process_capture)
_process_capture: "ProfileCapture | None" = None


def register_process_capture(capture: "ProfileCapture | None") -> None:
    """Make ``capture`` the capture every :class:`PerfPlane` in this
    process ticks from its hot loops.  The jax profiler is
    process-global (one trace window per process), so in-process
    deployments — client threads sharing the server process — close a
    server-armed ``steps=K`` window after K hot-loop steps.  Separate
    client processes have no registered capture (their steps cannot
    tick another process's profiler); there the window closes at the
    round boundary and profiles the server process."""
    global _process_capture
    _process_capture = capture


def process_capture() -> "ProfileCapture | None":
    return _process_capture


class ProfileCapture:
    """``POST /profile?steps=K`` arms a ``jax.profiler`` trace window
    opened at the next round boundary and closed after K hot-loop
    steps (or at the round's end, whichever comes first); the artifact
    lands under ``<out_dir>/round<r>/`` with a ``capture.json``
    manifest, so the directory is self-describing even if the XLA
    trace itself fails to materialize."""

    def __init__(self, out_dir: str | pathlib.Path, log=None):
        self.out_dir = pathlib.Path(out_dir)
        self._log = log
        self._lock = threading.Lock()
        self._armed_steps: int | None = None
        self._active_dir: pathlib.Path | None = None
        self._steps_left = 0
        self._t0 = 0.0

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._armed_steps is not None

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active_dir is not None

    def arm(self, steps: int = 1) -> dict:
        """Arm a capture window (idempotent re-arm updates K).  Called
        from the exporter's HTTP handler thread — just flips state."""
        steps = max(1, int(steps))
        with self._lock:
            self._armed_steps = steps
        if self._log is not None:
            self._log.info(f"profiler armed: {steps}-step capture at "
                           "the next round", "cyan")
        return {"armed": True, "steps": steps,
                "dir": str(self.out_dir)}

    def maybe_start(self, round_idx: int) -> bool:
        """Round boundary: open the trace window if armed."""
        with self._lock:
            if self._armed_steps is None or self._active_dir is not None:
                return False
            steps = self._armed_steps
            self._armed_steps = None
            target = self.out_dir / f"round{round_idx}"
            self._active_dir = target
            self._steps_left = steps
            self._t0 = time.time()
        try:
            target.mkdir(parents=True, exist_ok=True)
            import jax
            jax.profiler.start_trace(str(target))
        except Exception as e:  # noqa: BLE001 — a profiler failure
            # must not take the round down; the manifest records it
            self._write_manifest(target, round_idx, steps, error=str(e))
            with self._lock:
                self._active_dir = None
            return False
        if self._log is not None:
            self._log.info(f"profiler capture started -> {target}",
                           "cyan")
        self._round_idx = round_idx
        self._steps_total = steps
        return True

    def note_step(self) -> None:
        """Hot-loop tick; closes the window when K steps elapsed."""
        with self._lock:
            if self._active_dir is None:
                return
            self._steps_left -= 1
            done = self._steps_left <= 0
        if done:
            self.stop()

    def stop(self) -> None:
        """Close an open window (round end forces this)."""
        with self._lock:
            target = self._active_dir
            self._active_dir = None
        if target is None:
            return
        err = None
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            err = str(e)
        self._write_manifest(target, getattr(self, "_round_idx", None),
                             getattr(self, "_steps_total", None),
                             error=err)
        if self._log is not None:
            self._log.info(f"profiler capture written -> {target}",
                           "cyan")

    def _write_manifest(self, target: pathlib.Path, round_idx, steps,
                        error=None) -> None:
        try:
            target.mkdir(parents=True, exist_ok=True)
            rec = {"round": round_idx, "steps": steps,
                   "t_start": round(self._t0, 3),
                   "wall_s": round(time.time() - self._t0, 3)}
            if error:
                rec["error"] = error
            (target / "capture.json").write_text(json.dumps(rec))
        except OSError:
            pass


# --------------------------------------------------------------------------
# the facade
# --------------------------------------------------------------------------

class PerfPlane:
    """One participant's compute-attribution plane: step timer +
    compile watch + memory watch + MFU, emitting one ``kind=perf``
    record per round whose components sum to the round wall."""

    def __init__(self, participant: str, sample_every: int = 16,
                 datasheet: dict | None = None, gauges=None, hists=None,
                 faults=None, tracer=None, log=None,
                 enabled: bool = True,
                 capture: ProfileCapture | None = None):
        self.participant = participant
        self.enabled = enabled
        self.datasheet = dict(datasheet or {})
        self.gauges = gauges
        self.log = log
        self.capture = capture
        self.compile = CompileWatch(faults=faults, tracer=tracer,
                                    gauges=gauges, log=log)
        self.steps = SampledStepTimer(sample_every=sample_every, hists=hists,
                               gauges=gauges,
                               compile_overlap=self.compile.overlap)
        self.memory = MemoryWatch(gauges=gauges)
        self._peak_tflops: float | None = None
        self._peak_resolved = False
        self._t_round: float | None = None
        self._round_idx: int | None = None

    # -- lifecycle -----------------------------------------------------------

    def start_round(self, round_idx: int) -> None:
        if not self.enabled:
            return
        self._round_idx = round_idx
        self._t_round = time.perf_counter()
        self.steps.start_round(round_idx)
        self.compile.note_round(round_idx)
        if self.capture is not None:
            self.capture.maybe_start(round_idx)

    def note_step(self, t0: float, tree=None, n: int = 0) -> None:
        if not self.enabled:
            return
        self.steps.note_step(t0, tree=tree, n=n)
        if self.capture is not None:
            self.capture.note_step()

    def host(self):
        if not self.enabled:
            return contextlib.nullcontext()
        return self.steps.host()

    def wrap_runner(self, runner) -> None:
        if self.enabled:
            self.compile.wrap_runner(runner)

    # -- MFU -----------------------------------------------------------------

    def peak_tflops(self) -> float | None:
        """Datasheet peak for this process's device kind (cached)."""
        if not self._peak_resolved:
            self._peak_resolved = True
            try:
                import jax
                kind = jax.devices()[0].device_kind
            except Exception:  # noqa: BLE001 — no backend at all
                kind = "cpu"
            self._peak_tflops = resolve_peak_tflops(kind, self.datasheet)
        return self._peak_tflops

    # -- the round record ----------------------------------------------------

    def end_round(self, samples: int = 0,
                  wall_s: float | None = None) -> dict | None:
        """Close the round: sample HBM, compute the attribution and
        MFU, set the gauges, and return the ``kind=perf`` record (None
        when the plane is disabled or no round was started)."""
        if not self.enabled or self._t_round is None:
            return None
        # deliberately NOT stopping self.capture here: it is the
        # process-wide capture (shared by every in-proc client plane),
        # and the first client to finish its round must not truncate a
        # steps=K window the others are still ticking — the round loop
        # (loop.py) closes it at the round boundary, K hot-loop ticks
        # close it early
        wall = (wall_s if wall_s is not None
                else time.perf_counter() - self._t_round)
        att = self.steps.attribution(wall_s=wall)
        csnap = self.compile.snapshot()
        compile_s = csnap["compile_s_round"]
        device_est = att["device_est_s"]
        dispatch_s = att["dispatch_s"]
        host_s = att["host_s"]
        # the identity the attribution tests pin: compute + compile +
        # dispatch + host + wait == wall (wait = the unattributed rest:
        # queue/barrier/wire waits, control traffic).  In a pipelined
        # hot loop a sampled fence drains ALL in-flight steps, so the
        # extrapolated device estimate can overlap dispatch/host of
        # later steps and overshoot the wall — clamp compute to the
        # unattributed remainder (the overlapped part is not extra
        # wall time) and keep the raw estimate auditable.
        device_s = min(device_est,
                       max(0.0, wall - dispatch_s - host_s - compile_s))
        wait_s = max(0.0, wall - device_s - dispatch_s - host_s
                     - compile_s)
        rec: dict[str, Any] = {
            "v": PERF_SCHEMA_VERSION,
            "round": self._round_idx,
            "wall_s": round(wall, 6),
            "compute_s": round(device_s, 6),
            "compile_s": round(compile_s, 6),
            "dispatch_s": round(dispatch_s, 6),
            "host_s": round(host_s, 6),
            "wait_s": round(wait_s, 6),
            "steps": att["steps"],
            "sampled_steps": att["sampled_steps"],
            "sample_every": att["sample_every"],
            "samples": samples,
            "compiles": csnap["compiles"],
            "compile_s_total": csnap["compile_s_total"],
            "retraces": csnap["retraces"],
        }
        if device_est > device_s + 1e-6:
            rec["compute_est_s"] = round(device_est, 6)
        self._mem_sample()
        rec.update(self.memory.snapshot())
        flops = csnap["round_flops"]
        if flops:
            rec["flops"] = flops
            tflops = flops / max(wall, 1e-9) / 1e12
            rec["tflops_per_sec"] = round(tflops, 4)
            peak = self.peak_tflops()
            if peak:
                rec["mfu"] = round(tflops / peak, 5)
                rec["peak_tflops"] = peak
                if self.gauges is not None:
                    self.gauges.set("mfu", rec["mfu"])
        # compute rate: samples over the time the device/dispatcher was
        # actually busy — lets the fleet monitor tell slow-COMPUTE from
        # slow-WIRE stragglers (overall samples/s conflates them).
        # Uses the RAW device estimate: overlap clamped out of the
        # wall attribution above is still real device busy time.
        # No fenced step this round (steps < sample-every) means NO
        # device estimate — dispatch-only busy would inflate the rate
        # by orders of magnitude and flip _rate_why's compute-vs-wire
        # verdict, so the gauge is withheld until a fence lands
        busy = device_est + dispatch_s
        if samples and busy > 0 and att["sampled_steps"]:
            rec["compute_samples_per_s"] = round(samples / busy, 3)
            if self.gauges is not None:
                self.gauges.set("compute_samples_per_s",
                                rec["compute_samples_per_s"])
        self._t_round = None
        return rec

    def _mem_sample(self):
        try:
            return self.memory.sample()
        except Exception:  # noqa: BLE001 — watermark is best-effort
            return None


def make_perf_plane(cfg, participant: str, gauges=None, hists=None,
                    faults=None, tracer=None, log=None,
                    capture: ProfileCapture | None = None) -> PerfPlane:
    """Build a participant's perf plane from ``cfg.perf`` (tolerates
    configs predating the block: disabled plane, zero overhead)."""
    perf_cfg = getattr(cfg, "perf", None)
    if perf_cfg is None:
        return PerfPlane(participant, enabled=False)
    datasheet = getattr(perf_cfg, "datasheet", None)
    if datasheet is not None and not isinstance(datasheet, dict):
        # tuple-frozen YAML mapping-of-pairs form
        try:
            datasheet = dict(datasheet)
        except (TypeError, ValueError):
            datasheet = None
    return PerfPlane(
        participant,
        sample_every=getattr(perf_cfg, "sample_every", 16),
        datasheet=datasheet, gauges=gauges, hists=hists, faults=faults,
        tracer=tracer, log=log,
        enabled=bool(getattr(perf_cfg, "enabled", True)),
        capture=capture)


def perf_enabled(cfg) -> bool:
    """Whether the perf plane is on for ``cfg`` — shared by the client
    planes (via :func:`make_perf_plane`) and the server-side round loop
    (MemoryWatch + ``kind=perf`` records), so ``perf: {enabled:
    false}`` silences BOTH halves.  Configs predating the block have no
    plane at all."""
    perf_cfg = getattr(cfg, "perf", None)
    return (perf_cfg is not None
            and bool(getattr(perf_cfg, "enabled", True)))


def profile_output_dir(cfg, logger=None) -> pathlib.Path:
    """Where ``/profile`` captures land: the run-scoped output
    directory's ``profile/`` subdir when the logger has one, else
    ``{perf.profile-dir or log_path}/profile``."""
    perf_cfg = getattr(cfg, "perf", None)
    override = getattr(perf_cfg, "profile_dir", None) if perf_cfg else None
    if override:
        return pathlib.Path(override)
    base = getattr(logger, "output_dir", None)
    if base is None:
        base = pathlib.Path(getattr(cfg, "log_path", "."))
    return pathlib.Path(base) / "profile"
