"""Bounded process-wide memo for compiled/jitted bundles.

Shared by :mod:`split_learning_tpu.runtime.client` (ShardRunner jitted
ops) and :mod:`split_learning_tpu.runtime.context` (MeshContext
compiled steps): re-tracing an identical program costs seconds of pure
Python per rebuild on a 1-core host, and every re-plan / round / test
with the same geometry would otherwise repay it for the same HLO.
"""

from __future__ import annotations

from typing import Callable

_MISS = object()


def bounded_setdefault(cache: dict, max_size: int, key, build: Callable):
    """Return ``cache[key]``, building it with ``build()`` on a miss.

    FIFO-bounded and thread-tolerant: concurrent builders race benignly
    (``setdefault`` keeps one winner; the loser's build is wasted work,
    not an error) and eviction never raises — a racing evictor may
    already have removed the oldest key, or the dict may mutate under
    ``next(iter(...))``.  A legitimately-``None`` built value is a hit
    too (sentinel miss check), not an every-call rebuild.
    """
    hit = cache.get(key, _MISS)
    if hit is not _MISS:
        return hit
    value = build()
    while len(cache) >= max_size:
        try:
            cache.pop(next(iter(cache)), None)
        except (StopIteration, RuntimeError):
            break
    return cache.setdefault(key, value)
