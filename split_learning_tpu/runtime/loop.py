"""The global round loop: train → aggregate → validate → checkpoint.

Parity with the reference server's round handling
(``/root/reference/src/Server.py:155-210``): after each round's updates
are aggregated the full model is validated on the test set; a NaN/exploded
round logs "Training failed!" and is not checkpointed
(``:184-196``); otherwise the checkpoint is (over)written and the next
round begins; resume loads the checkpoint and continues
(``:230-256``).
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import time
from typing import Any

from split_learning_tpu.config import Config
from split_learning_tpu.runtime.checkpoint import (
    load_checkpoint, save_checkpoint,
)
from split_learning_tpu.runtime.context import TrainContext
from split_learning_tpu.runtime.log import Logger
from split_learning_tpu.runtime.plan import ClusterPlan
from split_learning_tpu.runtime.strategies import make_strategy
from split_learning_tpu.runtime.trace import StepTimer


@dataclasses.dataclass
class RoundRecord:
    round_idx: int
    ok: bool
    num_samples: int
    wall_s: float
    val_loss: float | None = None
    val_accuracy: float | None = None


@dataclasses.dataclass
class TrainResult:
    params: Any
    stats: Any
    history: list


def run_training(cfg: Config, ctx: TrainContext,
                 plans: list[ClusterPlan],
                 logger: Logger | None = None,
                 init_params: Any | None = None,
                 init_stats: Any | None = None) -> TrainResult:
    logger = logger or Logger.for_run(cfg, "server", console=False)
    strategy = make_strategy(cfg)
    # round tracing (runtime/spans.py): the context's tracer when it
    # has one (ProtocolContext), else a loop-owned one (in-process
    # mesh runs) closed on exit
    from split_learning_tpu.runtime.spans import make_tracer
    tracer = getattr(ctx, "tracer", None)
    own_tracer = tracer is None
    if own_tracer:
        tracer = make_tracer(cfg, "server")

    start_round = 0
    params, stats = init_params, init_stats
    if cfg.checkpoint.load:
        ck = load_checkpoint(cfg.checkpoint.directory, cfg.model_key)
        if ck is not None:
            params, stats = ck["params"], ck["batch_stats"]
            start_round = ck["round_idx"]
            logger.info(f"Loaded checkpoint at round {start_round}.",
                        "green")
    if params is None:
        variables = ctx.init_variables()
        params = variables["params"]
        stats = variables.get("batch_stats", {})
    stats = stats or {}

    for plan in plans:
        logger.info(
            f"Cluster {plan.cluster_id}: cuts={plan.cuts} "
            f"clients={[len(ids) for ids in plan.clients]} "
            f"rejected={plan.rejected}", "cyan")

    history: list[RoundRecord] = []
    timer = StepTimer()
    # compute-attribution plane (runtime/perf.py): the server side
    # tracks per-round HBM watermarks and drives the on-demand
    # profiler window the exporter's POST /profile armed (protocol
    # clients attribute their own hot loops and emit their own
    # kind=perf records; this one covers the server process)
    from split_learning_tpu.runtime.perf import MemoryWatch, perf_enabled
    # honor the plane's off switch server-side too: `perf: {enabled:
    # false}` must silence the per-round memory_stats()/live_arrays
    # walk and the kind=perf record stream, not just the client half
    # (the on-demand profiler capture stays independent — POST
    # /profile is its own opt-in)
    memwatch = (MemoryWatch(gauges=getattr(ctx, "gauges", None))
                if perf_enabled(cfg) else None)
    capture = getattr(ctx, "perf_capture", None)
    t_start = time.perf_counter()
    # one-slot async checkpoint writer: the save overlaps the next
    # round's training instead of blocking the loop (params trees are
    # immutable host/device arrays, safe to serialize from a thread);
    # one slot bounds memory and keeps saves ordered
    ck_pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    ck_future: concurrent.futures.Future | None = None
    try:
        for r in range(start_round, cfg.global_rounds):
            if r > start_round:
                # elastic membership (topology.elastic-join): late
                # registrations join, repeatedly-silent clients leave
                new_plans = ctx.refresh_plans(plans)
                if new_plans is not None:
                    plans = new_plans
                    for plan in plans:
                        logger.info(
                            f"Cluster {plan.cluster_id} (re-planned): "
                            f"cuts={plan.cuts} clients="
                            f"{[len(ids) for ids in plan.clients]}",
                            "cyan")
                # closed-loop scheduler (scheduler.enabled, protocol
                # backend): the round-boundary decision pass — online
                # clustering, straggler eviction/demotion, measured-
                # throughput cut re-planning — runs AFTER the elastic
                # refresh so it scores the membership that will
                # actually train
                schedule = getattr(ctx, "schedule_plans", None)
                if schedule is not None:
                    sched_plans = schedule(plans, r)
                    if sched_plans is not None:
                        plans = sched_plans
            if capture is not None:
                # armed via POST /profile: the window opens at this
                # round boundary and closes at the round's end (in the
                # in-process mesh it covers the compiled steps; in
                # protocol mode it profiles the server process)
                capture.maybe_start(r)
            # one span per round, with the loop phases as
            # children: the per-round anchor the critical-path
            # walker (tools/sl_trace.py) starts from
            with tracer.span("round", round=r) as round_span:
                t0 = time.perf_counter()
                with timer.phase("train"), \
                        tracer.span("train", round=r):
                    outcome = strategy.run_round(ctx, plans, r, params, stats)
                wall = time.perf_counter() - t0
                rec = RoundRecord(round_idx=r, ok=outcome.ok,
                                  num_samples=outcome.num_samples, wall_s=wall)
                if not outcome.ok:
                    logger.error(f"Round {r}: Training failed! "
                                 f"(NaN detected; aggregation skipped)")
                    history.append(rec)
                    # explicit kind stamp: these per-round records are
                    # what kind-keyed consumers (bench.py, sl_top's
                    # journal mode) select on
                    logger.metric(kind="round",
                                  **dataclasses.asdict(rec),
                                  phases=timer.summary())
                    timer.reset()  # don't leak this round's time onward
                    if capture is not None:
                        capture.stop()   # a failed round still lands
                                         # its profile artifact
                    # the failed round is the one an operator debugs:
                    # its spans must hit disk like a clean round's (the
                    # continue below skips the loop-tail flush; end()
                    # is idempotent, so the context exit stays a no-op)
                    round_span.end()
                    tracer.flush()
                    continue
                prev_params, prev_stats = params, stats
                params, stats = outcome.params, outcome.stats
                if outcome.validate and cfg.checkpoint.validate:
                    with timer.phase("validate"), \
                            tracer.span("validate", round=r):
                        val = ctx.validate(params, stats)
                    rec.val_loss, rec.val_accuracy = val.loss, val.accuracy
                    rec.ok = val.ok
                    logger.info(
                        f"Round {r}: samples={outcome.num_samples} "
                        f"val_loss={val.loss:.4f} val_acc={val.accuracy:.4f} "
                        f"({wall:.1f}s)", "green" if val.ok else "red")
                    if not val.ok:
                        # reference aborts on an exploded round
                        # (src/Server.py:185-187); keep the last good weights
                        # rather than training on from garbage
                        logger.error(f"Round {r}: Training failed! "
                                     f"(validation loss exploded)")
                        params, stats = prev_params, prev_stats
                else:
                    logger.info(f"Round {r}: samples={outcome.num_samples} "
                                f"({wall:.1f}s)", "green")
                if rec.ok and cfg.checkpoint.save:
                    with timer.phase("checkpoint"), \
                            tracer.span("checkpoint", round=r):
                        if ck_future is not None:
                            ck_future.result()  # surface errors; keep order
                        ck_future = ck_pool.submit(
                            save_checkpoint, cfg.checkpoint.directory,
                            cfg.model_key, params, stats, round_idx=r + 1)
                history.append(rec)
                logger.metric(kind="round", **dataclasses.asdict(rec),
                              phases=timer.summary(),
                              **({"train_detail": outcome.metrics}
                                 if outcome.metrics else {}))
                timer.reset()
            if capture is not None:
                capture.stop()
            if memwatch is not None:
                try:
                    memwatch.sample()
                except Exception:  # noqa: BLE001 — watermark best-effort
                    pass
                # server-side kind=perf record: round wall + HBM
                # watermark (protocol clients emit their own
                # attribution records)
                logger.metric(kind="perf", round_idx=r, v=1,
                              wall_s=round(rec.wall_s, 6),
                              **memwatch.snapshot())
            tracer.flush()
            if cfg.limited_time and (time.perf_counter() - t_start
                                     > cfg.limited_time):
                logger.warning(f"Wall-clock budget {cfg.limited_time}s "
                               f"exhausted at round {r}.")
                break
    finally:
        # an exception escaping the loop must not leave the
        # process-global jax profiler tracing (start_trace would then
        # fail forever after) — stop() is idempotent on a closed window
        if capture is not None:
            capture.stop()
        # drain on EVERY exit: a crash mid-round must still surface a
        # failed background save and join the worker thread (the
        # protocol server calls run_training repeatedly in-process)
        if ck_future is not None:
            ck_future.result()  # the last checkpoint must be durable
        ck_pool.shutdown(wait=True)
        if own_tracer:
            tracer.close()
        else:
            tracer.flush()
    return TrainResult(params=params, stats=stats, history=history)
