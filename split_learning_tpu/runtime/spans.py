"""Distributed round tracing: span journals + wire-propagated context.

``metrics.jsonl`` holds per-round aggregates and monotonic counters, but
nothing in it can answer "where did round N's 54 seconds go" — queue
wait, network, encode/decode and device time are indistinguishable once
summed.  This module is the attribution layer:

* :class:`Tracer` — one per participant.  Spans (name, participant,
  trace/span/parent IDs, t_start/duration, queue, frame kind, nbytes)
  are appended to a per-participant ``spans-{participant}.jsonl``
  journal by a thread-safe buffered :class:`SpanJournal`.  Parenting is
  implicit through a per-thread span stack (context-manager spans), or
  explicit for cross-participant edges.
* **Wire context** — :func:`pack_ctx` / :func:`unpack_ctx` encode a
  compact ``(trace_id, span_id, t_send)`` triple (32 bytes) that the
  TENSOR/chunk frame headers carry (``runtime/protocol.py``), so every
  Activation/Gradient/Update frame links the sender's *publish* span to
  the receiver's *consume* span: the merged trace gets a flow edge per
  data-plane frame, and ``t_send`` yields true per-frame RTT.
* ``tools/sl_trace.py`` merges the journals into a Chrome/Perfetto
  ``trace.json`` and walks the span graph backward for a per-round
  critical-path report.

Costs are kept off the hot path: a disabled tracer returns a shared
no-op span (no allocation beyond the call), sampling is a single RNG
draw, and journal writes buffer ``flush_every`` records between file
appends.  Timestamps are ``time.time()`` so spans from different
processes merge on one timeline; cross-*machine* deployments inherit
whatever clock skew NTP leaves (flow arrows stay correct — they bind
ids, not timestamps — but RTTs absorb the skew).
"""

from __future__ import annotations

import contextlib
import json
import pathlib
import random
import struct
import threading
import time
import uuid
from typing import Any

from split_learning_tpu.runtime import blackbox

#: spans.jsonl record schema version (bump on breaking change)
SCHEMA_VERSION = 1

# -- wire trace context -----------------------------------------------------
# Fixed 32 bytes: 16-byte trace id | 8-byte sender span id | f64 send
# time (epoch seconds).  Fixed size keeps frame lengths deterministic
# under chaos seeding (corruption positions depend on payload length).

_CTX = struct.Struct(">16s8sd")
CTX_BYTES = _CTX.size


def pack_ctx(trace_id: str, span_id: str, t_send: float | None = None
             ) -> bytes:
    """Encode a wire trace context (hex ids -> 32 opaque bytes)."""
    return _CTX.pack(bytes.fromhex(trace_id), bytes.fromhex(span_id),
                     time.time() if t_send is None else t_send)


def unpack_ctx(raw: bytes | None) -> tuple[str, str, float] | None:
    """Decode a wire trace context; None on absent/malformed input
    (a foreign or pre-tracing frame must degrade to "no edge", never
    raise into a decode path)."""
    if not raw or len(raw) != CTX_BYTES:
        return None
    tid, sid, t_send = _CTX.unpack(raw)
    return tid.hex(), sid.hex(), t_send


class SpanJournal:
    """Thread-safe buffered JSONL appender for span records.

    Buffers ``flush_every`` records between file appends so the hot
    path pays a dict + list append, not a syscall; ``flush`` is called
    at round boundaries and on close so a finished round's spans are
    durable even if the process later dies."""

    def __init__(self, path: str | pathlib.Path, flush_every: int = 128):
        self.path = pathlib.Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # created eagerly: a run-scoped compat symlink to this journal
        # must never dangle (tools glob then open the directory's
        # spans-*.jsonl, symlinks included)
        self.path.touch(exist_ok=True)
        self._flush_every = max(1, flush_every)
        self._lock = threading.Lock()
        self._buf: list[dict] = []
        self._closed = False

    def append(self, rec: dict) -> None:
        with self._lock:
            if self._closed:
                return
            self._buf.append(rec)
            if len(self._buf) < self._flush_every:
                return
            buf, self._buf = self._buf, []
        self._write(buf)

    def _write(self, buf: list[dict]) -> None:
        if not buf:
            return
        data = "".join(json.dumps(r) + "\n" for r in buf)
        with open(self.path, "a") as f:
            f.write(data)
            f.flush()

    def flush(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        self._write(buf)

    def close(self) -> None:
        self.flush()
        with self._lock:
            self._closed = True


class _NullSpan:
    """Shared no-op span: the disabled/unsampled fast path."""

    __slots__ = ()
    id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def end(self, **attrs) -> None:
        pass


NULL_SPAN = _NullSpan()


class Span:
    """One open span; ``end()`` (idempotent) writes the journal record.

    May be ended on a different thread than it was started on (the
    async sender finishes *publish* spans) — ``end`` touches no
    tracer thread-state."""

    __slots__ = ("_tracer", "name", "id", "parent", "t0", "attrs",
                 "_thread", "_done")

    def __init__(self, tracer: "Tracer", name: str, parent: str | None,
                 attrs: dict):
        self._tracer = tracer
        self.name = name
        self.id = uuid.uuid4().hex[:16]
        self.parent = parent
        self.t0 = time.time()
        self.attrs = attrs
        self._thread = threading.current_thread().name
        self._done = False

    def end(self, **attrs) -> None:
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        self._tracer._emit(self, time.time())

    def __enter__(self):
        self._tracer._push(self.id)
        return self

    def __exit__(self, *exc):
        self._tracer._pop()
        self.end()
        return False


class Tracer:
    """Per-participant span factory + journal.

    ``trace_id`` is run-scoped: the server generates one and broadcasts
    it in START (``extra["trace_id"]``) so every participant's journal
    — and every wire context — carries the same id even across
    processes (:meth:`adopt_trace_id`)."""

    def __init__(self, participant: str, enabled: bool = True,
                 sample_rate: float = 1.0,
                 journal_dir: str | pathlib.Path = ".",
                 trace_id: str | None = None, flush_every: int = 128):
        self.participant = participant
        self.enabled = enabled
        self.sample_rate = float(sample_rate)
        self.trace_id = trace_id or uuid.uuid4().hex
        self._tls = threading.local()
        self._journal = (SpanJournal(
            pathlib.Path(journal_dir) / f"spans-{participant}.jsonl",
            flush_every) if enabled else None)

    # -- parenting stack (per thread) ---------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, span_id: str | None) -> None:
        self._stack().append(span_id)

    def _pop(self) -> None:
        st = self._stack()
        if st:
            st.pop()

    def current_id(self) -> str | None:
        st = self._stack()
        return st[-1] if st else None

    # -- span creation ------------------------------------------------------

    def _sampled(self, always: bool) -> bool:
        if not self.enabled:
            return False
        if always or self.sample_rate >= 1.0:
            return True
        return random.random() < self.sample_rate

    def start(self, name: str, parent: str | None = None,
              always: bool = True, **attrs: Any):
        """Open a span (ended explicitly via ``span.end()``).  With
        ``always=False`` the configured sample rate applies — use for
        per-frame/per-batch spans; structural spans (rounds, phases)
        always record."""
        if not self._sampled(always):
            return NULL_SPAN
        if parent is None:
            parent = self.current_id()
        return Span(self, name, parent, attrs)

    def span(self, name: str, parent: str | None = None,
             always: bool = True, **attrs: Any):
        """Context-manager span; children opened on this thread inside
        the block inherit it as parent."""
        s = self.start(name, parent=parent, always=always, **attrs)
        if s is NULL_SPAN:
            return contextlib.nullcontext(NULL_SPAN)
        return s

    def record(self, name: str, t0: float, t1: float,
               parent: str | None = None, always: bool = False,
               **attrs: Any) -> str | None:
        """Write an already-timed span (the consume path measures the
        decode before it knows the message carried a context)."""
        if not self._sampled(always):
            return None
        s = Span(self, name, parent if parent is not None
                 else self.current_id(), attrs)
        s.t0 = t0
        s._done = True
        self._emit(s, t1)
        return s.id

    def wire_context(self, span) -> bytes:
        """Wire bytes linking ``span`` to its receiver-side consume
        span; empty (and free) when the span was not sampled."""
        if span is NULL_SPAN or span.id is None:
            return b""
        return pack_ctx(self.trace_id, span.id)

    def adopt_trace_id(self, trace_id: str) -> None:
        """Join the server's run-scoped trace (START extra)."""
        if trace_id:
            self.trace_id = trace_id

    # -- journal ------------------------------------------------------------

    def _emit(self, span: Span, t1: float) -> None:
        if self._journal is None:
            return
        rec = {"v": SCHEMA_VERSION, "trace": self.trace_id,
               "span": span.id, "parent": span.parent,
               "name": span.name, "part": self.participant,
               "thread": span._thread, "ts": round(span.t0, 6),
               "dur": round(max(0.0, t1 - span.t0), 6)}
        for k, v in span.attrs.items():
            if v is not None:
                rec[k] = v
        self._journal.append(rec)
        # flight-recorder feed: span close = "this phase just ran
        # here" — the blackbox ring's primary what-was-it-doing signal
        if blackbox.enabled():
            blackbox.record("span", name=span.name,
                            dur=rec["dur"],
                            queue=span.attrs.get("queue"),
                            nbytes=span.attrs.get("nbytes"),
                            round=span.attrs.get("round"))

    def flush(self) -> None:
        if self._journal is not None:
            self._journal.flush()

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()


def make_tracer(cfg, participant: str) -> Tracer:
    """Build a participant's tracer from ``cfg.observability`` (falls
    back to a disabled tracer when the config predates the block).

    Under ``observability.run-scoped`` the journal lands in the same
    ``artifacts/runs/<run_id>/`` directory as the logger's outputs
    (``runtime/log.py``), with a compat symlink at the flat path —
    one directory per run holds app.log + metrics.jsonl +
    spans-*.jsonl together."""
    obs = getattr(cfg, "observability", None)
    if obs is None:
        return Tracer(participant, enabled=False)
    journal_dir = pathlib.Path(obs.journal_dir or cfg.log_path)
    if obs.enabled and getattr(obs, "run_scoped", False):
        from split_learning_tpu.runtime.log import (
            compat_link, run_output_dir, write_run_owner,
        )
        out = run_output_dir(journal_dir)
        name = f"spans-{participant}.jsonl"
        try:
            out.mkdir(parents=True, exist_ok=True)
            ok = True
        except OSError:
            ok = False
        if ok:
            write_run_owner(out)
            # eager target so the link below never dangles
            (out / name).touch(exist_ok=True)
            if compat_link(journal_dir / name, out / name):
                journal_dir = out
    return Tracer(participant, enabled=obs.enabled,
                  sample_rate=obs.sample_rate,
                  journal_dir=journal_dir,
                  flush_every=obs.flush_every)
