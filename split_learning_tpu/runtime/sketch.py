"""Mergeable fleet-health sketches: the digest path's data structures.

PR 13 proved the *decision* loop flat to 10k clients, but the telemetry
substrate itself stayed O(clients) on one process: every client's
HEARTBEAT lands on the server's rpc pump, ``FleetMonitor`` keeps a
per-client ring-buffer series, and every ``/metrics`` scrape renders
one ``sl_client_*`` series per client.  At the 100k–1M tier all three
walls grow linearly.  This module is the fix's foundation: summaries
that are

* **deterministic** — same inputs, same bytes, whatever the fold order;
* **mergeable** — ``merge(a, b)`` loses nothing a flat pass would keep
  (state counts and counter sums are EXACT; quantiles are exact up to
  the fixed bucket width);
* **bounded** — a digest's size depends on the bucket count and the
  top-K, never on the client count behind it.

Pieces:

* :class:`ValueSketch` — log-bucket quantile sketch over positive
  values, reusing ``trace.py``'s geometric bucketing (factor
  ``2**0.25`` per bucket, same as
  :class:`~split_learning_tpu.runtime.trace.LatencyHistogram.BOUNDS`)
  so histograms fold WITHOUT loss: two sketches over the same bucket
  grid merge by adding counts, and a reported quantile is within ~19%
  (one bucket width) of the true value however many merges happened;
* :class:`WorstK` — bounded worst-straggler heap ordered by (health
  state severity, straggler score): the clients a merged digest still
  names individually, so the server's watchlist can keep exact state
  machines for exactly the clients that matter;
* :func:`merge_digests` — fold any number of digest dicts into one,
  exact counts/sums, sketch-merged quantiles, worst-K re-truncated;
* :data:`DIGEST_COUNTER_NAMES` / :data:`DIGEST_GAUGE_NAMES` — the
  counter/gauge vocabulary the digest path mints, declared here and
  statically held to the ``runtime/trace.py`` registries by the
  ``counters`` analyzer's CT004 rule (a digest counter that is not a
  declared FaultCounters name would silently vanish from /metrics).

No protocol, no jax imports: a digest travels the wire as a PLAIN DICT
inside a ``FleetDigest`` frame (the restricted unpickler's vocabulary
stays closed), and everything here is plain python + math.
"""

from __future__ import annotations

import math
from typing import Any, Iterable

#: geometric bucket factor — 2**(1/_BUCKETS_PER_OCTAVE) per bucket,
#: matching trace.py LatencyHistogram's 2**0.25 spacing so the two
#: families quantize identically
_BUCKETS_PER_OCTAVE = 4

#: quantile sketch schema version (travels inside the digest dict)
SKETCH_V = 1

#: counters the digest path increments (held to
#: ``trace.FAULT_COUNTER_NAMES`` by the CT004 analyzer rule):
#: duplicate/reordered FleetDigest frames the server rejected, and
#: clients re-pointed to direct heartbeats because their digest node
#: died
DIGEST_COUNTER_NAMES = frozenset({
    "stale_digests", "digest_fallbacks",
})

#: gauges the digest path sets (held to ``trace.GAUGE_NAMES`` by
#: CT004): live digest-reporting nodes, clients covered by digests,
#: and the server watchlist's current size
DIGEST_GAUGE_NAMES = frozenset({
    "fleet_digest_nodes", "fleet_digest_clients", "fleet_watchlist",
})


def bucket_index(value: float) -> int:
    """Bucket of a positive value: ``i`` covers
    ``[2**(i/4), 2**((i+1)/4))``.  Deterministic across platforms for
    the float range telemetry produces."""
    return math.floor(_BUCKETS_PER_OCTAVE * math.log2(value))


def bucket_value(i: int) -> float:
    """Representative value: geometric mean of the bucket's edges
    (same convention as ``LatencyHistogram._bucket_value``)."""
    lo = 2.0 ** (i / _BUCKETS_PER_OCTAVE)
    hi = 2.0 ** ((i + 1) / _BUCKETS_PER_OCTAVE)
    return math.sqrt(lo * hi)


class ValueSketch:
    """Log-bucket quantile sketch over positive values.

    Sparse: buckets are a ``{index: count}`` dict, so the footprint is
    the number of OCCUPIED buckets (a fleet whose rates span 6 orders
    of magnitude still costs ~80 entries).  Zero/negative/non-finite
    observations land in a dedicated ``zero`` bin that quantile
    queries rank below every positive bucket — an idle client is the
    worst rate, not a dropped sample.  NOT thread-safe: a sketch is
    built by one thread and merged by value."""

    __slots__ = ("counts", "zero", "n", "total")

    def __init__(self):
        self.counts: dict[int, int] = {}
        self.zero = 0          # observations <= 0 (or non-finite)
        self.n = 0             # total observations
        self.total = 0.0       # exact running sum (mean survives merge)

    def observe(self, value: float | None) -> None:
        if value is None:
            return
        v = float(value)
        self.n += 1
        if not math.isfinite(v) or v <= 0.0:
            self.zero += 1
            return
        self.total += v
        i = bucket_index(v)
        self.counts[i] = self.counts.get(i, 0) + 1

    def merge(self, other: "ValueSketch | dict | None") -> "ValueSketch":
        """Fold another sketch in (lossless: same bucket grid)."""
        if other is None:
            return self
        if isinstance(other, dict):
            o = ValueSketch.from_dict(other)
            if o is None:
                return self
            other = o
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + c
        self.zero += other.zero
        self.n += other.n
        self.total += other.total
        return self

    def quantile(self, q: float) -> float | None:
        """Approximate q-th percentile (q in [0, 100]); None when
        empty.  Error is bounded by one bucket width (~19%)."""
        if self.n == 0:
            return None
        rank = max(1, math.ceil(self.n * q / 100.0))
        if rank <= self.zero:
            return 0.0
        cum = self.zero
        for i in sorted(self.counts):
            cum += self.counts[i]
            if cum >= rank:
                return bucket_value(i)
        return bucket_value(max(self.counts)) if self.counts else 0.0

    def mean(self) -> float | None:
        return self.total / self.n if self.n else None

    def as_dict(self) -> dict:
        """Wire form (plain builtins; bucket keys as strings so the
        dict survives JSON round-trips in metrics.jsonl)."""
        return {"v": SKETCH_V, "n": self.n, "zero": self.zero,
                "total": round(self.total, 6),
                "b": {str(i): c for i, c in sorted(self.counts.items())}}

    @classmethod
    def from_dict(cls, d: Any) -> "ValueSketch | None":
        """Tolerant decode: a foreign/garbage dict degrades to None,
        never raises into the server's rpc pump."""
        if not isinstance(d, dict):
            return None
        try:
            out = cls()
            out.n = int(d.get("n", 0))
            out.zero = int(d.get("zero", 0))
            out.total = float(d.get("total", 0.0))
            out.counts = {int(k): int(c)
                          for k, c in (d.get("b") or {}).items()}
            return out
        except (TypeError, ValueError):
            return None


# --------------------------------------------------------------------------
# worst-K straggler heap
# --------------------------------------------------------------------------

#: health states in severity order (mirrors telemetry.HEALTH_STATES —
#: re-declared here so this module stays import-light; the telemetry
#: tests assert the two agree)
_SEVERITY = {"healthy": 0, "degraded": 1, "straggler": 2, "lost": 3}


def _worst_key(entry: dict) -> tuple:
    """Sort key, worst first: higher state severity, then lower
    straggler score, then client id (the deterministic tiebreak)."""
    score = entry.get("score")
    return (-_SEVERITY.get(entry.get("state", "healthy"), 0),
            score if score is not None else math.inf,
            entry.get("client") or "")


class WorstK:
    """Bounded list of the K worst clients, each entry carrying enough
    of the client's last snapshot (``view``) for the server to seed an
    exact watchlist state machine from it.  Merging two WorstK's and
    truncating is associative and order-independent (ties broken by
    client id), and a duplicate client id keeps its WORST entry."""

    __slots__ = ("k", "entries")

    def __init__(self, k: int):
        self.k = max(0, int(k))
        self.entries: list[dict] = []

    def add(self, client: str, state: str, score: float | None,
            view: dict | None = None) -> None:
        self.entries.append({"client": client, "state": state,
                             "score": score, "view": view or {}})

    def merge(self, other: "WorstK | Iterable[dict] | None") -> "WorstK":
        if other is None:
            return self
        self.entries.extend(other.entries if isinstance(other, WorstK)
                            else list(other))
        return self

    def top(self) -> list[dict]:
        best: dict[str, dict] = {}
        for e in self.entries:
            cid = e.get("client")
            if not cid:
                continue
            cur = best.get(cid)
            if cur is None or _worst_key(e) < _worst_key(cur):
                best[cid] = e
        ranked = sorted(best.values(), key=_worst_key)
        return ranked[:self.k]


# --------------------------------------------------------------------------
# digest folding
# --------------------------------------------------------------------------

#: the sketch-valued fields of a digest dict
_SKETCH_FIELDS = ("rate", "crate")
#: bounded lengths of the list-valued digest fields after a merge
MAX_TRANSITIONS = 64


def empty_digest() -> dict:
    return {"v": 1, "node": None, "t": 0.0, "seq": 0, "clients": 0,
            "states": {}, "counters": {}, "samples": 0,
            "rate": ValueSketch().as_dict(),
            "crate": ValueSketch().as_dict(),
            "stages": {}, "worst": [], "transitions": []}


def decode_digest(d: Any) -> dict | None:
    """Tolerant validation of a wire digest dict (the FleetDigest
    frame's payload): required fields with the right shapes, or None."""
    if not isinstance(d, dict):
        return None
    try:
        t = float(d.get("t", 0.0))
        seq = int(d.get("seq", 0))
        states = d.get("states") or {}
        counters = d.get("counters") or {}
        if not isinstance(states, dict) or not isinstance(counters,
                                                         dict):
            return None
        out = dict(empty_digest())
        out.update(d)
        out["t"], out["seq"] = t, seq
        out["clients"] = int(d.get("clients", 0))
        out["samples"] = int(d.get("samples", 0))
        out["states"] = {str(s): int(n) for s, n in states.items()}
        out["counters"] = {str(k): int(v)
                           for k, v in counters.items()}
        return out
    except (TypeError, ValueError):
        return None


def merge_digests(digests: Iterable[dict], k: int = 16) -> dict:
    """Fold node digests into one fleet view.  Exact where the inputs
    are exact (state counts, counter sums, samples, client count),
    sketch-merged for the quantiles, worst-K re-ranked across nodes.
    Order/duplicate handling is the CALLER's job (the FleetMonitor
    keeps one latest digest per node, seq-guarded) — given one digest
    per node this fold is order-invariant."""
    out = empty_digest()
    out["node"] = "*"
    rate, crate = ValueSketch(), ValueSketch()
    worst = WorstK(k)
    stages: dict[str, dict] = {}
    transitions: list[dict] = []
    for d in digests:
        if not d:
            continue
        out["t"] = max(out["t"], float(d.get("t", 0.0)))
        out["clients"] += int(d.get("clients", 0))
        out["samples"] += int(d.get("samples", 0))
        for s, n in (d.get("states") or {}).items():
            out["states"][s] = out["states"].get(s, 0) + int(n)
        for name, v in (d.get("counters") or {}).items():
            out["counters"][name] = out["counters"].get(name, 0) \
                + int(v)
        rate.merge(d.get("rate"))
        crate.merge(d.get("crate"))
        worst.merge(d.get("worst") or [])
        for st, sd in (d.get("stages") or {}).items():
            ent = stages.setdefault(str(st), {
                "n": 0, "crate": ValueSketch(),
                "step_ms": ValueSketch()})
            ent["n"] += int(sd.get("n", 0))
            ent["crate"].merge(sd.get("crate"))
            ent["step_ms"].merge(sd.get("step_ms"))
        transitions.extend(d.get("transitions") or [])
    out["rate"] = rate.as_dict()
    out["crate"] = crate.as_dict()
    out["worst"] = worst.top()
    out["stages"] = {
        st: {"n": ent["n"], "crate": ent["crate"].as_dict(),
             "step_ms": ent["step_ms"].as_dict()}
        for st, ent in sorted(stages.items())}
    transitions.sort(key=lambda r: (r.get("t", 0.0),
                                    r.get("client") or ""))
    out["transitions"] = transitions[-MAX_TRANSITIONS:]
    return out


def digest_quantiles(digest: dict, qs=(50, 95)) -> dict:
    """Fleet-level quantile gauges from a (merged) digest —
    what /metrics renders instead of 100k per-client series."""
    out: dict = {}
    for field in _SKETCH_FIELDS:
        sk = ValueSketch.from_dict(digest.get(field))
        if sk is None or sk.n == 0:
            continue
        for q in qs:
            v = sk.quantile(q)
            if v is not None:
                out[f"{field}_p{q}"] = round(v, 4)
    return out
