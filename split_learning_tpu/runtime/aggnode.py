"""Standalone aggregator-node process (``aggregation.remote``).

PR 9's aggregator tree ran its L1 folds as server THREADS — the fold
fan-in was constant but every partial still folded inside one process,
and the tree died with the server.  This module promotes the tree's
interior nodes to **standalone processes** connected over the existing
TCP broker (``tools/sl_aggregator.py`` /
``python -m split_learning_tpu.aggregator``):

* the node builds its transport with
  :func:`~split_learning_tpu.runtime.chaos.make_runtime_transport`, so
  the Reliable/Chaos/Async stacks compose exactly as they do for a
  client — a chaos sweep faults the aggregate plane of a remote tree
  the same way it faults a thread-mode one;
* it announces itself with an
  :class:`~split_learning_tpu.runtime.protocol.AggHello` on the rpc
  queue and then heartbeats like any client
  (:class:`~split_learning_tpu.runtime.telemetry.TelemetryEmitter`
  with ``kind="agg_node"``) — liveness is the HEARTBEAT/FleetMonitor
  plane, and a node the monitor marks ``lost`` (or whose spawned
  process exits) triggers the server's counted direct-to-root
  fallback drain, not a barrier stall;
* per train_cluster invocation the server sends one
  :class:`~split_learning_tpu.runtime.protocol.AggAssign` naming the
  node's groups (any level — an L2 group folds its children's
  PartialAggregates).  The node's fold worker drives one
  :class:`~split_learning_tpu.runtime.aggregate.L1Aggregator` PER
  GROUP — the same object the thread mode runs, minus the thread —
  multiplexed over a single dedicated broker connection (zero-timeout
  gets round-robin across the group queues), so a node serving
  hundreds of groups costs two connections, not hundreds;
* flushes cascade level-ascending on
  :class:`~split_learning_tpu.runtime.protocol.AggFlush` (or the
  assignment deadline): level-1 groups flush first so interior groups
  can still fold the children's partials before their own forced
  flush;
* per assignment the node emits one ``kind=agg_node`` metrics record
  (folded count, ingress/egress bytes, fold wall) and mirrors the
  numbers into gauges that ride its heartbeats — ``/fleet`` and
  ``sl_top`` can name a slow aggregator the way they name a slow
  client.
"""

from __future__ import annotations

import argparse
import threading
import time

from split_learning_tpu.config import Config, from_yaml
from split_learning_tpu.runtime import aggregate as agg_plane
from split_learning_tpu.runtime import blackbox
from split_learning_tpu.runtime.log import Logger
from split_learning_tpu.runtime.protocol import (
    AggAssign, AggFlush, AggHello, BlackboxDump, FleetDigest,
    FrameAssembler, Heartbeat, Stop, digest_queue, encode, reply_queue,
    RPC_QUEUE,
)

#: seconds an interior group keeps polling for its children's partials
#: after the flush cascade released the level below it
FLUSH_GRACE_S = 2.0


class DigestWorker(threading.Thread):
    """Hierarchical heartbeat roll-up (``observability.digest-interval``):
    drains the node's :func:`digest_queue` — where the server routed
    its assigned clients' HEARTBEAT frames via START ``extra.digest``
    — into a node-local :class:`~split_learning_tpu.runtime.telemetry
    .FleetMonitor` (the SAME state machine the server runs, so the
    rolled-up per-state counts are exact vs a flat oracle), and
    publishes one :class:`FleetDigest` frame per interval on the rpc
    queue.  Root ingest is thereby O(nodes + top-K), not O(clients).

    Owns its transport (``digest_bus``): a blocking control-loop get
    and a zero-timeout fold sweep must never share a TCP socket with
    this drain (the same ownership rule as the fold worker's)."""

    #: heartbeat frames drained per sweep before the publish check
    DRAIN_BATCH = 512

    def __init__(self, node: "AggregatorNode", interval: float):
        super().__init__(daemon=True, name=f"{node.node_id}-digest")
        from split_learning_tpu.runtime.telemetry import FleetMonitor
        self.node = node
        self.interval = max(float(interval), 1e-3)
        self.queue = digest_queue(node.node_id)
        obs = node.cfg.observability
        # the node-local monitor mirrors the server's thresholds so
        # digest states are exactly what a flat FleetMonitor fed the
        # same heartbeats would report
        self.monitor = FleetMonitor(
            interval=obs.heartbeat_interval,
            liveness_timeout=obs.liveness_timeout,
            log=None, faults=node.faults)
        self._asm = FrameAssembler(faults=node.faults)
        # NOT named _stop: threading.Thread's join() path calls an
        # internal _stop() on 3.10 — shadowing it with an Event breaks
        # every join of this thread
        self._halt = threading.Event()
        self._seq = 0

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:
        next_pub = time.monotonic() + self.interval
        while not self._halt.is_set():
            drained = self._drain()
            self.monitor.note_pump()
            if time.monotonic() >= next_pub:
                next_pub += self.interval
                try:
                    self.publish_digest()
                except Exception as e:  # noqa: BLE001 — transport
                    # gone: the server's node-death fallback re-points
                    # the clients; this thread just winds down
                    self.node.log.warning(f"digest publish failed: {e}")
                    return
            if not drained:
                self._halt.wait(min(self.interval / 4, 0.05))

    def _drain(self) -> bool:
        drained = False
        for _ in range(self.DRAIN_BATCH):
            raw = self.node.digest_bus.get(self.queue, timeout=0.0)
            if raw is None:
                break
            drained = True
            try:
                msg = self._asm.feed(raw)
            except Exception:  # noqa: BLE001 — one corrupt heartbeat
                self.node.faults.inc("corrupt_rejected")
                continue
            if isinstance(msg, Heartbeat):
                self.monitor.note_heartbeat(msg.client_id,
                                            msg.telemetry)
        return drained

    def publish_digest(self) -> None:
        """Advance the local state machine and ship one digest (also
        called once at teardown so the last interval isn't lost)."""
        t0 = time.time()
        self.monitor.advance()
        self._seq += 1
        digest = self.monitor.build_digest(self.node.node_id,
                                           self._seq)
        self.node.bus.publish(RPC_QUEUE, encode(FleetDigest(
            node_id=self.node.node_id, digest=digest)))
        self.node.gauges.set("fleet_digest_clients",
                             digest.get("clients", 0))
        self.node.tracer.record(
            "agg.digest", t0, time.time(), always=True, seq=self._seq,
            clients=digest.get("clients", 0))


class AssignmentWorker(threading.Thread):
    """One invocation's fold worker: drives the assignment's
    L1Aggregator objects (any level) over a dedicated transport,
    publishing each group's partial the moment it completes."""

    def __init__(self, node: "AggregatorNode", assign: AggAssign):
        super().__init__(daemon=True,
                         name=f"{node.node_id}-fold-g{assign.gen}")
        self.node = node
        self.gen = assign.gen
        self.round_idx = assign.round_idx
        self.flush = threading.Event()
        spec = None
        if assign.codec:
            from split_learning_tpu.runtime.codec.specs import parse_spec
            spec = parse_spec(assign.codec)
        bases = assign.bases or {}
        deadline = time.monotonic() + float(assign.deadline_s)
        self.workers: list[agg_plane.L1Aggregator] = []
        for d in assign.groups or []:
            g = agg_plane.AggGroup.from_dict(d)
            out_q = (RPC_QUEUE if g.parent is None
                     else agg_plane.aggregate_queue(assign.cluster,
                                                    g.parent))
            self.workers.append(agg_plane.L1Aggregator(
                node.fold_bus, cluster=assign.cluster, group=g,
                members=g.members, gen=assign.gen, deadline=deadline,
                log=node.log, faults=node.faults,
                chunk_bytes=assign.chunk_bytes, out_queue=out_q,
                codec=spec, base=bases.get(g.stage),
                base_gen=assign.gen if spec is not None
                and spec.kind == "delta" else None))

    def run(self) -> None:
        t0 = time.perf_counter()
        tw0 = time.time()
        try:
            self._fold_loop()
            tw1 = time.time()
            self.node.tracer.record(
                "agg.fold", tw0, tw1, always=True, gen=self.gen,
                round=self.round_idx, groups=len(self.workers))
            self._flush_cascade()
            self.node.tracer.record(
                "agg.flush", tw1, time.time(), always=True,
                gen=self.gen, round=self.round_idx,
                flushed=sum(1 for w in self.workers if w.flushed))
        except Exception as e:  # noqa: BLE001 — a dead transport mid-
            # round means the node is effectively dead for this gen;
            # the server's fallback drain recovers the groups
            self.node.log.warning(
                f"fold worker gen={self.gen} died: {e}")
            return
        self._report(time.perf_counter() - t0)

    def _pending(self) -> list:
        return [w for w in self.workers if not w.flushed]

    def _fold_loop(self) -> None:
        bus = self.node.fold_bus
        while not self.flush.is_set():
            live = self._pending()
            if not live:
                return
            if all(time.monotonic() >= w.deadline for w in live):
                return
            progress = False
            for w in live:
                raw = bus.get(w.queue, timeout=0.0)
                if raw is None:
                    continue
                progress = True
                w.feed_raw(raw)
                if w.complete:
                    w.publish()
            if not progress:
                self.flush.wait(0.004)

    def _flush_cascade(self) -> None:
        """Forced flush, level-ascending: flushing an interior group
        before its children have published would silently drop whole
        subtrees, so each level flushes and the next gets a bounded
        grace to drain the partials that flush produced."""
        bus = self.node.fold_bus
        levels = sorted({w.group.level for w in self._pending()})
        for i, lv in enumerate(levels):
            for w in self._pending():
                if w.group.level == lv:
                    w.publish()
            rest = [w for w in self._pending() if w.group.level > lv]
            if not rest:
                return
            grace = time.monotonic() + FLUSH_GRACE_S
            while time.monotonic() < grace:
                progress = False
                for w in list(rest):
                    if w.flushed:
                        continue
                    raw = bus.get(w.queue, timeout=0.0)
                    if raw is None:
                        continue
                    progress = True
                    w.feed_raw(raw)
                    if w.complete:
                        w.publish()
                if all(w.flushed for w in rest):
                    break
                if not progress:
                    time.sleep(0.004)
        for w in self._pending():
            w.publish()

    def _report(self, fold_s: float) -> None:
        node = self.node
        folded = sum(len(w.seen) for w in self.workers)
        ingress = sum(w.ingress_bytes for w in self.workers)
        egress = sum(w.egress_bytes for w in self.workers)
        node.gauges.set("agg_node_folded", folded)
        node.gauges.set("agg_node_ingress_bytes", ingress)
        node.gauges.set("agg_node_egress_bytes", egress)
        node.gauges.set("agg_node_fold_s", round(fold_s, 6))
        node.gauges.set("agg_node_groups", len(self.workers))
        node.log.metric(
            kind="agg_node", node=node.node_id, gen=self.gen,
            round_idx=self.round_idx, groups=len(self.workers),
            folded=folded, ingress_bytes=ingress, egress_bytes=egress,
            fold_s=round(fold_s, 6),
            incomplete=sum(1 for w in self.workers if not w.complete))
        # round boundary for this node: make the gen's spans durable
        # now, not at whatever flush_every batch boundary comes next
        node.tracer.flush()


class AggregatorNode:
    """The node process: adoption hello, heartbeats, assignment loop.

    ``transport``/``fold_transport`` default to fresh
    ``make_runtime_transport`` stacks (two broker connections: the
    control loop's blocking get must not starve the fold worker's
    zero-timeout sweeps); tests pass a shared in-proc bus for both.
    """

    def __init__(self, cfg: Config, node_id: str, transport=None,
                 fold_transport=None, digest_transport=None,
                 logger: Logger | None = None):
        self.cfg = cfg
        self.node_id = node_id
        from split_learning_tpu.runtime.trace import FaultCounters
        self.faults = FaultCounters()
        obs = getattr(cfg, "observability", None)
        digest_interval = (obs.digest_interval
                           if obs is not None else 0.0)
        # close-at-teardown only covers stacks this node CREATED: an
        # injected transport (tests, in-proc cells) is shared — the
        # same ownership rule as L1Aggregator's owns_bus
        self._owns_buses = transport is None
        if transport is None:
            from split_learning_tpu.runtime.chaos import (
                make_runtime_transport,
            )
            transport = make_runtime_transport(cfg, node_id,
                                               faults=self.faults)
            if fold_transport is None:
                fold_transport = make_runtime_transport(
                    cfg, f"{node_id}.fold", faults=self.faults)
            if digest_transport is None and digest_interval > 0:
                digest_transport = make_runtime_transport(
                    cfg, f"{node_id}.digest", faults=self.faults)
        self.bus = transport
        self.fold_bus = (fold_transport if fold_transport is not None
                         else transport)
        self.digest_bus = (digest_transport
                           if digest_transport is not None
                           else transport)
        self.log = logger or Logger.for_run(cfg, node_id, console=False)
        # span-plane membership: the node's fold/flush/digest phases
        # journal into spans-{node_id}.jsonl so sl_trace merges the
        # aggregator tier into the fleet timeline (the trace id is
        # adopted per-assignment from AggAssign-carrying runs' config;
        # absent that, the journal still merges by wall clock)
        from split_learning_tpu.runtime.spans import make_tracer
        self.tracer = make_tracer(cfg, node_id)
        self._asm = FrameAssembler(faults=self.faults)
        self._stop = threading.Event()
        from split_learning_tpu.runtime.telemetry import (
            GaugeSet, TelemetryEmitter,
        )
        self.gauges = GaugeSet()
        interval = obs.heartbeat_interval if obs is not None else 0.0
        self.emitter = TelemetryEmitter(
            node_id, self._beat, interval=interval, faults=self.faults,
            gauges=self.gauges, kind="agg_node")
        # hierarchical heartbeat roll-up: one FleetDigest per
        # observability.digest-interval over the clients whose
        # heartbeats the server routed to this node's digest queue
        self.digester = (DigestWorker(self, digest_interval)
                         if digest_interval > 0 else None)

    def _beat(self, snapshot: dict) -> None:
        self.bus.publish(RPC_QUEUE, encode(Heartbeat(
            client_id=self.node_id, telemetry=snapshot)))

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        self.bus.publish(RPC_QUEUE, encode(AggHello(
            node_id=self.node_id)))
        self.log.sent("AGGHELLO")
        self.emitter.start()
        if self.digester is not None:
            self.digester.start()
        worker: AssignmentWorker | None = None
        try:
            while not self._stop.is_set():
                raw = self.bus.get(reply_queue(self.node_id),
                                   timeout=0.25)
                if raw is None:
                    continue
                try:
                    msg = self._asm.feed(raw)
                except Exception as e:  # noqa: BLE001 — one corrupt
                    # frame costs one message, not the node
                    self.faults.inc("corrupt_rejected")
                    self.log.warning(f"dropping undecodable frame: {e}")
                    continue
                if msg is None:
                    continue
                if isinstance(msg, Stop):
                    self.log.received(f"STOP ({msg.reason})")
                    break
                if isinstance(msg, BlackboxDump):
                    # server-initiated fleet snapshot: flush this
                    # node's flight recorder alongside everyone else's
                    blackbox.record("dump_request", reason=msg.reason)
                    blackbox.dump(msg.reason or "fleet_snapshot")
                    continue
                if isinstance(msg, AggAssign):
                    self.log.received(
                        f"AGGASSIGN gen={msg.gen} "
                        f"groups={len(msg.groups or [])}")
                    if worker is not None and worker.is_alive():
                        # a new assignment supersedes the old round:
                        # flush it out rather than strand its groups.
                        # The old worker MUST be gone before the new
                        # one starts — both would otherwise drive the
                        # same fold transport from two threads (the
                        # exact concurrent-socket use thread-mode L1s
                        # avoid by owning their own stacks).  The
                        # cascade is bounded (FLUSH_GRACE_S per level
                        # + publish time), so 60 s only fails on a
                        # wedged transport — then folding the new gen
                        # is impossible anyway: drop the assignment
                        # and let the server's fallback drain recover.
                        worker.flush.set()
                        worker.join(timeout=60.0)
                        if worker.is_alive():
                            self.log.warning(
                                f"fold worker gen={worker.gen} still "
                                f"running; dropping assignment "
                                f"gen={msg.gen} (server fallback "
                                "will drain the groups)")
                            continue
                    worker = AssignmentWorker(self, msg)
                    worker.start()
                elif isinstance(msg, AggFlush):
                    self.log.received(f"AGGFLUSH gen={msg.gen}")
                    if worker is not None and worker.gen == msg.gen:
                        worker.flush.set()
        finally:
            if worker is not None and worker.is_alive():
                worker.flush.set()
                worker.join(timeout=10.0)
            if self.digester is not None:
                self.digester.stop()
                self.digester.join(timeout=5.0)
                try:
                    # last interval's heartbeats must not vanish with
                    # the node: one final digest before teardown
                    self.digester.publish_digest()
                except Exception:  # noqa: BLE001 — transport already
                    pass           # gone; the server's fallback covers
            self.emitter.stop()
            self.tracer.close()
            if self._owns_buses:
                for bus in {
                        id(self.bus): self.bus,
                        id(self.fold_bus): self.fold_bus,
                        id(self.digest_bus): self.digest_bus}.values():
                    try:
                        bus.close()
                    except Exception:  # noqa: BLE001 — teardown
                        pass           # best-effort

            self.log.close()


def write_node_config(cfg: Config, path) -> None:
    """Persist a config for spawned aggregator subprocesses.  JSON is
    a YAML subset, so ``from_yaml`` reads it back; tuples become lists
    (``_freeze`` re-tuples them on load)."""
    import json

    from split_learning_tpu.config import to_dict
    with open(path, "w") as f:
        json.dump(to_dict(cfg), f, default=list)


def spawn_node(config_path, node_id: str):
    """Spawn one aggregator subprocess (tcp transport).  The node is
    host-only — JAX_PLATFORMS is pinned to cpu unless the caller set
    it — and inherits stdio so its tracebacks surface in CI logs."""
    import os
    import subprocess
    import sys
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "split_learning_tpu.aggregator",
         "--config", str(config_path), "--node-id", node_id], env=env)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Standalone split-learning aggregator node "
                    "(aggregation.remote).")
    ap.add_argument("--config", default="config.yaml")
    ap.add_argument("--node-id", default="aggregator_node_0")
    args = ap.parse_args(argv)
    cfg = from_yaml(args.config)
    blackbox.install(cfg, args.node_id, role="agg_node")
    node = AggregatorNode(cfg, args.node_id)
    node.run()


if __name__ == "__main__":
    main()
