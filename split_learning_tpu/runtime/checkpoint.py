"""Checkpoint / resume.

Parity with the reference's single-file whole-model checkpoints
(``/root/reference/src/Server.py:190-193`` save after every successful
round; ``:230-256`` load + shard-extract at round start; delete the file
to reset, README.md:173-177).  Here the full param pytree (+ batch stats +
round counter) is written with orbax; shard extraction is
:func:`~split_learning_tpu.models.split.shard_params` pytree slicing —
the dict-key matching the reference does by hand.

Checkpoints are named ``{MODEL}_{DATASET}`` under the configured
checkpoint root (the reference's ``{model}_{data}.pth`` naming).  A
msgpack fallback (flax.serialization) covers environments where orbax is
unusable; load auto-detects the format.

Crash atomicity: a save never touches the live checkpoint.  The tree is
written to a hidden slot directory (``.{name}.data0``/``.data1``,
alternating) and published by atomically replacing the ``{name}``
symlink (``os.replace`` of a fresh symlink — one rename syscall).  A
process killed at ANY point leaves either the previous complete
checkpoint or the new complete checkpoint visible, never a torn one;
:func:`load_checkpoint` additionally treats an unreadable/truncated
checkpoint as absent (warn + ``None``) instead of raising, so a corrupt
file can never wedge a restart.
"""

from __future__ import annotations

import json
import os
import pathlib
import shutil
import warnings
from typing import Any

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is baked into the image
    _HAVE_ORBAX = False


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)


def checkpoint_path(directory: str | pathlib.Path,
                    model_key: str) -> pathlib.Path:
    return pathlib.Path(directory).resolve() / model_key


def _write_tree(target: pathlib.Path, tree: Any) -> None:
    if _HAVE_ORBAX:
        ocp.PyTreeCheckpointer().save(target, tree, force=True)
    else:  # pragma: no cover
        import flax.serialization
        target.mkdir(parents=True, exist_ok=True)
        (target / "state.msgpack").write_bytes(
            flax.serialization.to_bytes(tree))


def _publish(path: pathlib.Path, slot_name: str) -> None:
    """Atomically point the live ``path`` symlink at ``slot_name``."""
    staged = path.parent / f".{path.name}.lnk"
    try:
        staged.unlink()
    except FileNotFoundError:
        pass
    os.symlink(slot_name, staged)
    if path.exists() and not path.is_symlink():
        # legacy real-directory layout: one-time migration (the only
        # non-atomic window this scheme ever has)
        shutil.rmtree(path)
    os.replace(staged, path)


def save_checkpoint(directory: str | pathlib.Path, model_key: str,
                    params: Any, batch_stats: Any | None = None,
                    round_idx: int = 0, extra: dict | None = None) -> None:
    path = checkpoint_path(directory, model_key)
    path.parent.mkdir(parents=True, exist_ok=True)
    tree = {"params": _to_host(params),
            "batch_stats": _to_host(batch_stats or {}),
            "meta": {"round_idx": np.int64(round_idx)}}
    # write into the slot NOT currently live, then flip the symlink —
    # the previous checkpoint stays intact until the new one is complete
    live = os.readlink(path) if path.is_symlink() else None
    slot_name = (f".{model_key}.data1"
                 if live == f".{model_key}.data0"
                 else f".{model_key}.data0")
    tmp = path.parent / f".{model_key}.tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    _write_tree(tmp, tree)
    final = path.parent / slot_name
    shutil.rmtree(final, ignore_errors=True)
    os.replace(tmp, final)
    _publish(path, slot_name)
    if extra:
        meta = path.parent / f"{model_key}.meta.json"
        staged = path.parent / f".{model_key}.meta.json.tmp"
        staged.write_text(json.dumps(extra))
        os.replace(staged, meta)


def load_checkpoint(directory: str | pathlib.Path,
                    model_key: str) -> dict | None:
    """Returns {params, batch_stats, round_idx}, or None when the
    checkpoint is absent OR unreadable (torn write from a hard crash,
    bit rot): a corrupt checkpoint warns and is treated as no
    checkpoint rather than wedging the restart."""
    path = checkpoint_path(directory, model_key)
    if not path.exists():   # dangling symlink also reads as absent
        return None
    try:
        if (path / "state.msgpack").exists():  # pragma: no cover
            import flax.serialization
            tree = flax.serialization.msgpack_restore(
                (path / "state.msgpack").read_bytes())
        elif _HAVE_ORBAX:
            tree = ocp.PyTreeCheckpointer().restore(path)
        else:  # pragma: no cover
            return None
        return {"params": tree["params"],
                "batch_stats": tree.get("batch_stats") or {},
                "round_idx": int(tree["meta"]["round_idx"])}
    except Exception as e:  # noqa: BLE001 — any torn/corrupt state
        warnings.warn(
            f"checkpoint at {path} is unreadable ({type(e).__name__}: "
            f"{e}); ignoring it and starting fresh", RuntimeWarning,
            stacklevel=2)
        return None


def save_sidecar_arrays(directory: str | pathlib.Path, name: str,
                        arrays: dict[str, Any]) -> None:
    """Atomically persist a small named-array sidecar (e.g. a client's
    wire-codec error-feedback residuals, ``runtime/codec/sparse.py``):
    write ``.{name}.npz.tmp`` then one ``os.replace`` — the same
    crash-atomicity contract as the model checkpoint, without the slot
    machinery (a sidecar is one small file)."""
    root = pathlib.Path(directory).resolve()
    root.mkdir(parents=True, exist_ok=True)
    tmp = root / f".{name}.npz.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **{k: np.asarray(v) for k, v in arrays.items()})
    os.replace(tmp, root / f"{name}.npz")


def load_sidecar_arrays(directory: str | pathlib.Path,
                        name: str) -> dict | None:
    """Sidecar arrays, or None when absent OR unreadable (torn write:
    warn and treat as absent, mirroring :func:`load_checkpoint`)."""
    path = pathlib.Path(directory).resolve() / f"{name}.npz"
    if not path.exists():
        return None
    try:
        with np.load(path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}
    except Exception as e:  # noqa: BLE001 — any torn/corrupt state
        warnings.warn(
            f"sidecar at {path} is unreadable ({type(e).__name__}: "
            f"{e}); ignoring it", RuntimeWarning, stacklevel=2)
        return None


def delete_checkpoint(directory: str | pathlib.Path,
                      model_key: str) -> None:
    """Reference's "delete the .pth to reset" (README.md:173-177)."""
    path = checkpoint_path(directory, model_key)
    if path.is_symlink():
        path.unlink()
    elif path.exists():
        shutil.rmtree(path)
    for p in path.parent.glob(f".{model_key}.*"):
        # slot dirs, tmp dir, staged links
        if p.is_dir() and not p.is_symlink():
            shutil.rmtree(p, ignore_errors=True)
        else:
            try:
                p.unlink()
            except OSError as e:
                # a leftover slot means the NEXT save may publish into
                # a dirty directory — surface it instead of silence
                warnings.warn(f"could not remove checkpoint debris "
                              f"{p}: {e}", RuntimeWarning, stacklevel=2)
