"""Checkpoint / resume.

Parity with the reference's single-file whole-model checkpoints
(``/root/reference/src/Server.py:190-193`` save after every successful
round; ``:230-256`` load + shard-extract at round start; delete the file
to reset, README.md:173-177).  Here the full param pytree (+ batch stats +
round counter) is written with orbax; shard extraction is
:func:`~split_learning_tpu.models.split.shard_params` pytree slicing —
the dict-key matching the reference does by hand.

Checkpoints are directories named ``{MODEL}_{DATASET}`` under the
configured checkpoint root (the reference's ``{model}_{data}.pth``
naming).  A msgpack fallback (flax.serialization) covers environments
where orbax is unusable; load auto-detects the format.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is baked into the image
    _HAVE_ORBAX = False


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)


def checkpoint_path(directory: str | pathlib.Path,
                    model_key: str) -> pathlib.Path:
    return pathlib.Path(directory).resolve() / model_key


def save_checkpoint(directory: str | pathlib.Path, model_key: str,
                    params: Any, batch_stats: Any | None = None,
                    round_idx: int = 0, extra: dict | None = None) -> None:
    path = checkpoint_path(directory, model_key)
    path.parent.mkdir(parents=True, exist_ok=True)
    tree = {"params": _to_host(params),
            "batch_stats": _to_host(batch_stats or {}),
            "meta": {"round_idx": np.int64(round_idx)}}
    if _HAVE_ORBAX:
        ckpt = ocp.PyTreeCheckpointer()
        ckpt.save(path, tree, force=True)
    else:  # pragma: no cover
        import flax.serialization
        path.mkdir(parents=True, exist_ok=True)
        (path / "state.msgpack").write_bytes(
            flax.serialization.to_bytes(tree))
    if extra:
        (path.parent / f"{model_key}.meta.json").write_text(
            json.dumps(extra))


def load_checkpoint(directory: str | pathlib.Path,
                    model_key: str) -> dict | None:
    """Returns {params, batch_stats, round_idx} or None if absent."""
    path = checkpoint_path(directory, model_key)
    if not path.exists():
        return None
    if (path / "state.msgpack").exists():  # pragma: no cover
        import flax.serialization
        tree = flax.serialization.msgpack_restore(
            (path / "state.msgpack").read_bytes())
    elif _HAVE_ORBAX:
        tree = ocp.PyTreeCheckpointer().restore(path)
    else:  # pragma: no cover
        return None
    return {"params": tree["params"],
            "batch_stats": tree.get("batch_stats") or {},
            "round_idx": int(tree["meta"]["round_idx"])}


def delete_checkpoint(directory: str | pathlib.Path,
                      model_key: str) -> None:
    """Reference's "delete the .pth to reset" (README.md:173-177)."""
    import shutil
    path = checkpoint_path(directory, model_key)
    if path.exists():
        shutil.rmtree(path)
