"""Execution contexts: how a round strategy actually trains a cluster.

A :class:`TrainContext` exposes two operations to the round strategies
(:mod:`split_learning_tpu.runtime.strategies`):

* ``train_cluster(plan, params, stats, ...) -> list[Update]`` — run one
  round (or ``epochs`` epochs) of split training for one cluster and
  return per-(logical client, stage) shard updates — the same artifact
  the reference server collects from UPDATE messages
  (``/root/reference/src/Server.py:155-170``);
* ``validate(params, stats) -> ValResult`` — full-model test pass
  (``src/val/get_val.py``).

:class:`MeshContext` is the TPU-native backend: the whole cluster is ONE
jitted SPMD program on a (client, stage) mesh (see
:mod:`split_learning_tpu.parallel.pipeline`).  Logical clients beyond the
physical device budget are processed in column chunks; a cluster whose
stage count exceeds the device budget chains stages on-device as virtual
pipeline stages (cuts and shard extraction unchanged — split fwd/bwd is
numerically the one-stage-per-device program).

The multi-process protocol backend (real clients over a transport) lives
in :mod:`split_learning_tpu.runtime.server` and satisfies the same
interface.
"""

from __future__ import annotations

import dataclasses
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from split_learning_tpu.config import Config
from split_learning_tpu.data import make_data_loader, subset_seed
from split_learning_tpu.models import build_model, shard_params
from split_learning_tpu.models.split import SplitModel
from split_learning_tpu.parallel.mesh import make_mesh, stage_ranges
from split_learning_tpu.parallel.pipeline import (
    PipelineModel, make_lora_train_step, make_train_step, shard_to_mesh,
    stack_for_clients,
)
from split_learning_tpu.runtime.memo import bounded_setdefault
from split_learning_tpu.runtime.plan import ClusterPlan
from split_learning_tpu.runtime.protocol import Update
from split_learning_tpu.runtime.validation import (
    ValResult, dataset_for_model, dataset_kwargs_for_model,
)


def make_optimizer(learning, lr: float | None = None):
    """Optimizer from a LearningConfig (reference: SGD+momentum for VGG
    ``src/train/VGG16.py:62``, AdamW for BERT/KWT ``src/train/BERT.py:69``).

    ``adamw-zero1`` resolves here to the bf16-moment AdamW: the stage
    sharding itself lives in the pipelined step
    (``MeshContext._compiled`` routes to ``make_zero1_train_step``);
    every other consumer (protocol ShardRunner, axes steps, validation)
    gets the memory-halved moments without the mesh machinery.
    """
    rate = lr if lr is not None else learning.learning_rate
    if learning.optimizer in ("adamw-bf16", "adamw-zero1"):
        from split_learning_tpu.parallel.zero import adamw_bf16_states
        opt = adamw_bf16_states(rate, weight_decay=learning.weight_decay)
    elif learning.optimizer == "adamw":
        opt = optax.adamw(rate, weight_decay=learning.weight_decay)
    else:
        opt = optax.sgd(rate, momentum=learning.momentum)
    if learning.clip_grad_norm:
        opt = optax.chain(
            optax.clip_by_global_norm(learning.clip_grad_norm), opt)
    return opt


def client_groups(n_columns: int, n_logical: int) -> list[list[int]]:
    """Partition mesh client columns into n_logical contiguous groups."""
    n_logical = max(1, min(n_logical, n_columns))
    bounds = [round(i * n_columns / n_logical)
              for i in range(n_logical + 1)]
    return [list(range(bounds[i], bounds[i + 1]))
            for i in range(n_logical)]


#: process-wide compiled-step memo (see MeshContext._cache_scope);
#: bounded FIFO — entries hold compiled executables
_GLOBAL_STEP_CACHE: dict = {}
_GLOBAL_STEP_CACHE_MAX = 32


class TrainContext:
    # True when "clients" persist shard weights between train_cluster
    # calls (remote protocol clients); False when every round rebuilds
    # client state from the server's trees (in-process mesh columns).
    # FLEX-style strategies use this to decide whether weights must be
    # re-pushed every round.
    clients_hold_state = False

    def init_variables(self) -> dict:
        raise NotImplementedError

    def train_cluster(self, plan: ClusterPlan, params, stats, *,
                      round_idx: int = 0, epochs: int = 1,
                      client_subset: list | None = None,
                      per_client_params: dict | None = None,
                      lr: float | None = None,
                      sync_all_later_stages: bool = False) -> list[Update]:
        raise NotImplementedError

    def validate(self, params, stats) -> ValResult:
        raise NotImplementedError

    def refresh_plans(self, plans: list[ClusterPlan]
                      ) -> list[ClusterPlan] | None:
        """Between-round membership hook: return replacement plans when
        the live client set changed (elastic join/prune), else None.
        The mesh backend's membership is fixed at planning time."""
        return None

    def shutdown(self) -> None:
        pass


class MeshContext(TrainContext):
    """In-process compiled-mesh backend."""

    def __init__(self, cfg: Config, devices=None):
        self.cfg = cfg
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.model_kwargs = dict(cfg.model_kwargs or {})
        if cfg.compute_dtype == "bfloat16":
            self.model_kwargs.setdefault("dtype", jnp.bfloat16)
        self.full_model: SplitModel = build_model(
            cfg.model_key, **self.model_kwargs)
        self.specs = self.full_model.specs
        self.dataset = dataset_for_model(cfg.model_key)
        self.dataset_kwargs = dataset_kwargs_for_model(
            cfg.model_key, self.model_kwargs)
        self._loader_cache: dict = {}
        self._example = self._example_struct()
        # compiled steps are memoized PROCESS-wide: a fresh MeshContext
        # per round/run (the normal pattern — and every test) would
        # otherwise re-trace identical programs, seconds of pure Python
        # each on a 1-core host.  The scope tuple captures everything a
        # step closure reads from this context besides the per-call key.
        self._cache_scope = (
            cfg.model_key,
            repr(sorted(self.model_kwargs.items(), key=repr)),
            repr(dataclasses.asdict(cfg.learning)),
            tuple(self._example.shape), str(self._example.dtype),
            tuple(str(d) for d in self.devices),
        )

    def _step_cached(self, key: tuple):
        return _GLOBAL_STEP_CACHE.get(self._cache_scope + key)

    def _step_store(self, key: tuple, value):
        # one shared eviction/race implementation (runtime/memo.py)
        return bounded_setdefault(_GLOBAL_STEP_CACHE,
                                  _GLOBAL_STEP_CACHE_MAX,
                                  self._cache_scope + key, lambda: value)

    # -- model/data geometry ------------------------------------------------

    def _example_struct(self) -> jax.ShapeDtypeStruct:
        mb = self.cfg.learning.batch_size
        ds = make_data_loader(self.dataset, 1, train=False,
                              synthetic_size=self.cfg.synthetic_size or 64,
                              dataset_kwargs=self.dataset_kwargs)
        x, _ = next(iter(ds))
        arr = np.asarray(x)
        return jax.ShapeDtypeStruct((mb,) + arr.shape[1:], arr.dtype)

    def init_variables(self, rng=None) -> dict:
        rng = rng if rng is not None else jax.random.key(self.cfg.seed)
        x = jnp.zeros(self._example.shape, self._example.dtype)
        return self.full_model.init(rng, x, train=False)

    def _loader(self, client_key: str, label_counts: np.ndarray,
                round_idx: int = 0):
        refresh = self.cfg.distribution.refresh
        key = (client_key, tuple(np.asarray(label_counts).tolist()),
               round_idx if refresh else 0)
        if key not in self._loader_cache:
            if refresh:
                # evict this client's prior-round loaders: each holds a
                # materialized subset copy and is never reused
                for k in [k for k in self._loader_cache
                          if k[0] == client_key]:
                    del self._loader_cache[k]
            seed = subset_seed(self.cfg.seed, client_key, round_idx,
                               refresh)
            self._loader_cache[key] = make_data_loader(
                self.dataset, self.cfg.learning.batch_size,
                distribution=np.asarray(label_counts), train=True,
                seed=seed, synthetic_size=self.cfg.synthetic_size,
                dataset_kwargs=self.dataset_kwargs)
        return self._loader_cache[key]

    # params above this, on the CPU backend, force a 1-wide stage axis
    # (stages chained on-device, cuts preserved): XLA's CPU collectives
    # abort the process when one rendezvous participant is >40 s late
    # (rendezvous.cc termination timeout), and a heavy pipeline stage per
    # scan tick on oversubscribed virtual devices blows that budget.
    # Tiny test/dryrun models stay under it and keep exercising the real
    # ppermute pipeline path.
    _CPU_PIPELINE_PARAM_LIMIT = 2_000_000

    def _param_count(self) -> int:
        if not hasattr(self, "_n_params"):
            shapes = jax.eval_shape(self.init_variables)
            self._n_params = int(sum(
                np.prod(leaf.shape)
                for leaf in jax.tree_util.tree_leaves(
                    shapes["params"])))
        return self._n_params

    def _parallel_axis(self) -> tuple[str, int] | None:
        """Config-selected intra-client axis: ("model"|"seq"|"expert", n)."""
        t = self.cfg.topology
        if t.tensor_parallel > 1:
            return ("model", t.tensor_parallel)
        if t.sequence_parallel > 1:
            return ("seq", t.sequence_parallel)
        if t.expert_parallel > 1:
            return ("expert", t.expert_parallel)
        return None

    def _geometry(self, plan: ClusterPlan, n_active: int):
        """(C_phys, S_phys, physical cuts, tp, sp, ep) fitted to the
        device budget.

        Cuts are ALWAYS preserved: when the device budget (or the CPU
        rendezvous limit below) cannot give every stage its own device,
        the stage axis shrinks to the largest divisor of the stage count
        that fits and stages are chained on-device as virtual pipeline
        stages (same split semantics, microbatch gradient accumulation,
        no cross-device stage collectives at axis width 1).

        ``tensor-parallel`` with cut layers COMPOSES with the pipeline
        (VERDICT r3 weak #3): the mesh grows a ``model`` axis and each
        (client, stage) cell becomes a TP group — ``tp`` in the return
        is that axis width.  ``sequence-parallel`` with cut layers
        likewise COMPOSES (VERDICT r4 item 4): the mesh grows a ``seq``
        axis, stage hops move per-device sequence blocks, and ring
        attention runs over ``seq`` inside each stage — ``sp`` is that
        axis width.  ``expert-parallel`` with cut layers ALSO composes
        (VERDICT r4 item 5): the mesh grows an ``expert`` axis
        (GSPMD-auto, like ``model``) and each stage's MoE dispatch/
        combine all-to-alls are derived by XLA inside the manual
        pipeline — ``ep`` is that width."""
        par = self._parallel_axis()
        D = len(self.devices)
        tp = sp = ep = 1
        if par is not None:
            name, n = par
            if n > D:
                raise ValueError(
                    f"topology.{name}-parallel={n} exceeds the "
                    f"{D}-device budget")
            if not plan.cuts:
                # axes path: intra-client axis first, remaining devices
                # form the client axis; cuts stay virtual (full model
                # per TP/seq/expert group — split semantics live in
                # shard extraction)
                return (max(1, min(n_active, D // n)), 1,
                        list(plan.cuts), 1, 1, 1)
            if name == "model":
                tp = n   # PP x TP: each (client, stage) cell = TP group
            elif name == "seq":
                sp = n   # PP x SP: each cell = ring-attention group
            else:
                ep = n   # PP x EP: each cell = expert-dispatch group
        S = len(plan.cuts) + 1
        par_w = tp * sp * ep
        budget = min(S, D // par_w)
        if (jax.default_backend() == "cpu"
                and self._param_count() > self._CPU_PIPELINE_PARAM_LIMIT
                and not self.cfg.topology.force_pipeline):
            budget = 1  # heavy stages on CPU: chain locally (see above)
        s_phys = max(a for a in range(1, budget + 1) if S % a == 0)
        c_phys = max(1, min(n_active, D // (s_phys * par_w)))
        return c_phys, s_phys, list(plan.cuts), tp, sp, ep

    def _compiled_axes(self, plan: ClusterPlan, c_phys: int,
                       par: tuple[str, int], lr: float | None):
        """Step for the config-surface TP/SP/EP axes (VERDICT r2 item 4):
        mesh (client, model|seq|expert), full model per group, same
        calling convention as the pipelined step."""
        import types
        from jax.sharding import Mesh

        name, n = par
        lrn = self.cfg.learning
        if lrn.lora_rank > 0:
            raise ValueError(
                "lora_rank > 0 is not supported together with "
                "tensor/sequence/expert-parallel axes")
        key = (plan.cluster_id, c_phys, name, n, lr, "axes")
        cached = self._step_cached(key)
        if cached is not None:
            return cached
        mesh = Mesh(
            np.array(self.devices[:c_phys * n]).reshape(c_phys, n),
            ("client", name))
        optimizer = make_optimizer(lrn, lr)
        mk = dict(self.model_kwargs)
        if name == "seq":
            mk["seq_axis"] = "seq"
        try:
            model = build_model(self.cfg.model_key, **mk)
        except TypeError as e:
            raise ValueError(
                f"model {self.cfg.model_key} does not support "
                f"{name}-parallel (builder rejected {mk}): {e}") from e
        if name == "seq":
            from split_learning_tpu.parallel.sequence import (
                make_sp_train_step,
            )
            step = make_sp_train_step(model, optimizer, mesh)
        else:
            from split_learning_tpu.parallel.axes import (
                make_axes_train_step,
            )
            if name == "model":
                from split_learning_tpu.parallel.tensor import tp_spec
                step = make_axes_train_step(model, optimizer, mesh,
                                            tp_spec, "model")
            else:
                from split_learning_tpu.parallel.expert import ep_spec
                step = make_axes_train_step(model, optimizer, mesh,
                                            ep_spec, "expert")
        pipe = types.SimpleNamespace(num_microbatches=lrn.control_count,
                                     mb_size=lrn.batch_size)
        return self._step_store(key, (mesh, pipe, optimizer, step))

    def _compiled(self, plan: ClusterPlan, c_phys: int, s_phys: int,
                  cuts_phys: list, lr: float | None,
                  sync_map_key: tuple, client_sync: dict | None,
                  tp: int = 1, sp: int = 1, ep: int = 1):
        par = self._parallel_axis()
        if par is not None and tp == 1 and sp == 1 and ep == 1:
            return self._compiled_axes(plan, c_phys, par, lr)
        lrn = self.cfg.learning
        use_lora = lrn.lora_rank > 0
        use_zero = lrn.optimizer == "adamw-zero1"
        if use_lora and (tp > 1 or sp > 1 or ep > 1):
            raise ValueError(
                "lora_rank > 0 is not supported together with "
                "tensor-parallel, sequence-parallel or expert-parallel "
                "pipeline composition (adapter kernels have no "
                "sharding rules)")
        key = (plan.cluster_id, c_phys, s_phys, tuple(cuts_phys), lr,
               sync_map_key, use_lora, tp, use_zero, sp, ep)
        cached = self._step_cached(key)
        if cached is not None:
            return cached
        mesh = make_mesh(c_phys, s_phys, self.devices,
                         tensor_parallel=tp, seq_parallel=sp,
                         expert_parallel=ep)
        example, seq_axis = self._example, None
        if sp > 1:
            # PP x SP: the pipeline is built on the per-device sequence
            # BLOCK; make_train_step shards x/labels over `seq`
            if example.ndim != 2:
                raise ValueError(
                    "sequence-parallel with cut-layers needs a token "
                    f"model (got example shape {example.shape})")
            if example.shape[1] % sp:
                raise ValueError(
                    f"sequence length {example.shape[1]} not divisible "
                    f"by sequence-parallel={sp}")
            example = jax.ShapeDtypeStruct(
                (example.shape[0], example.shape[1] // sp),
                example.dtype)
            seq_axis = "seq"
        def build_pipe():
            return PipelineModel(
                self.cfg.model_key, cuts=cuts_phys,
                example_input=example,
                num_microbatches=lrn.control_count,
                remat=lrn.remat,
                model_kwargs=self.model_kwargs, seq_axis=seq_axis)

        if seq_axis is not None:
            # scope the rewrite to the SP path: an unrelated TypeError
            # (e.g. a typo'd model kwarg on plain PP) must keep its own
            # message
            try:
                pipe = build_pipe()
            except TypeError as e:
                raise ValueError(
                    f"model {self.cfg.model_key} does not support "
                    f"sequence-parallel (no seq_axis): {e}") from e
        else:
            pipe = build_pipe()
        if use_zero and (tp > 1 or sp > 1 or ep > 1):
            raise ValueError(
                "adamw-zero1 is not supported together with "
                "tensor-parallel, sequence-parallel or expert-parallel "
                "pipeline composition (the flat moment shards are "
                "sized to unsharded params; use adamw-bf16 instead)")
        if use_zero:
            # ZeRO-1 from YAML (VERDICT r3 item 3): moments flattened,
            # bf16, sharded over `stage`; the facade keeps the generic
            # `optimizer.init` + stack_for_clients call sites working
            from split_learning_tpu.parallel.zero import (
                make_zero1_train_step, shard_zero1_to_mesh,
                zero1_init_facade,
            )
            optimizer = zero1_init_facade(s_phys)
            # the zero state has its OWN mesh placement (moments
            # sharded (client, stage)): the generic client-sharded
            # placement would replicate full-size moments per stage
            # device — the exact buffer ZeRO-1 exists to eliminate
            optimizer.shard_opt_to_mesh = shard_zero1_to_mesh
            step = make_zero1_train_step(
                pipe, mesh,
                learning_rate=(lr if lr is not None
                               else lrn.learning_rate),
                weight_decay=lrn.weight_decay,
                client_sync=client_sync)
        elif use_lora:
            optimizer = make_optimizer(lrn, lr)
            step = make_lora_train_step(
                pipe, optimizer, mesh, lora_alpha=lrn.lora_alpha,
                lora_rank=lrn.lora_rank, client_sync=client_sync)
        else:
            optimizer = make_optimizer(lrn, lr)
            step = make_train_step(pipe, optimizer, mesh,
                                   client_sync=client_sync)
        return self._step_store(key, (mesh, pipe, optimizer, step))

    def _lora_partition(self, tree):
        """(frozen, trainable) for one client's base tree: adapters over
        target kernels, model's final (classifier) layer unfrozen —
        mirrors the protocol ShardRunner partition, including its
        no-target fallback to full training.

        Adapter init is seeded from cfg.seed alone — NOT per client:
        sync groups (shared later stages) require every column in a
        group to hold identical shard params, and grouped gradient
        means only preserve that when the inits match too.  The merged
        model starts at the base weights either way (b = 0)."""
        import warnings
        from split_learning_tpu.ops.lora import lora_init, split_frozen
        lrn = self.cfg.learning
        frozen, head = split_frozen(tree, [self.specs[-1].name])
        if not hasattr(self, "_lora_adapters"):
            # adapters depend only on kernel SHAPES + the global seed:
            # compute once per context, reuse every column/chunk/round
            self._lora_adapters = lora_init(
                jax.random.key(self.cfg.seed), frozen,
                targets=lrn.lora_targets, rank=lrn.lora_rank)
            if not self._lora_adapters:
                warnings.warn(
                    "lora_rank set but no target kernels in this model; "
                    "training full parameters instead", stacklevel=3)
        if not self._lora_adapters:
            return {}, {"lora": {}, "head": tree}
        return frozen, {"lora": self._lora_adapters, "head": head}

    def _sync_map(self, plan: ClusterPlan, c_phys: int, n_real: int,
                  sync_all: bool) -> tuple[dict | None, tuple]:
        """Per-layer client-axis sync groups for shared later stages.

        Only the first ``n_real`` columns are grouped; padded duplicate
        columns (short tail chunks) get singleton groups so their
        gradients never enter a shared-stage mean."""
        if n_real == 1 and c_phys == 1:
            return None, ()
        ranges = stage_ranges(len(self.specs), plan.cuts)
        sync: dict = {}
        items = []
        for s in range(2, len(ranges) + 1):
            n_logical = 1 if sync_all else max(1, len(plan.clients[s - 1]))
            if n_logical >= n_real:
                continue  # every column its own logical client: no sync
            groups = client_groups(n_real, n_logical) + [
                [i] for i in range(n_real, c_phys)]
            a, b = ranges[s - 1]
            for spec in self.specs[a:b]:
                if spec.make is None:
                    continue
                sync[spec.name] = groups
                items.append((spec.name, tuple(map(tuple, groups))))
        return (sync or None), tuple(items)

    # -- the round ----------------------------------------------------------

    def _drive_columns(self, step, loaders, c_phys, M, mb, epochs,
                       round_idx, params_c, opt_c, stats_c, *,
                       frozen_c=None, timings: dict | None = None):
        """Feed host batches through the compiled step for ``epochs``.

        Returns (params_c, opt_c, stats_c, loss_host, consumed):
        device trees after the last step, the final per-column loss as a
        host array (the round's NaN sentinel), and per-column DISTINCT
        sample counts — data_count semantics (src/train/VGG16.py:109): a
        loader shorter than the M-batch draw restarts mid-step, and
        those redraws must not inflate the client's aggregation weight,
        so each column is capped at its loader's own epoch (and dataset)
        size.

        ``timings``, when given, accumulates wall-clock attribution:
        ``host_data_s`` (batch build + host->device handoff),
        ``dispatch_s`` (async step-call returns), ``device_sync_s``
        (final loss fetch — absorbs queued device execution).
        """
        steps_per_epoch = max(1, min(len(ld) for ld in loaders) // M)
        rngs = jax.vmap(jax.random.key)(jnp.arange(c_phys)
                                        + round_idx * 1000)
        loss = None
        consumed = np.zeros(c_phys, dtype=np.int64)
        for i, ld in enumerate(loaders):
            consumed[i] = epochs * min(steps_per_epoch * M * mb,
                                       ld.samples_per_epoch,
                                       len(ld.dataset))
        t_data = t_dispatch = 0.0
        for _ in range(epochs):
            iters = [iter(ld) for ld in loaders]
            for _ in range(steps_per_epoch):
                t0 = time.perf_counter()
                xs, ys = [], []
                for it_i, it in enumerate(iters):
                    bx, by = [], []
                    for _ in range(M):
                        try:
                            b = next(it)
                        except StopIteration:
                            it = iters[it_i] = iter(loaders[it_i])
                            b = next(it)
                        bx.append(np.asarray(b[0]))
                        by.append(np.asarray(b[1]))
                    xs.append(np.stack(bx))
                    ys.append(np.stack(by))
                x = jnp.asarray(np.stack(xs))
                labels = jnp.asarray(np.stack(ys).astype(np.int32))
                t1 = time.perf_counter()
                if frozen_c is not None:
                    params_c, opt_c, stats_c, loss = step(
                        frozen_c, params_c, opt_c, stats_c, x,
                        labels, rngs)
                else:
                    params_c, opt_c, stats_c, loss = step(
                        params_c, opt_c, stats_c, x, labels, rngs)
                t2 = time.perf_counter()
                t_data += t1 - t0
                t_dispatch += t2 - t1
        t3 = time.perf_counter()
        loss_h = (np.asarray(loss) if loss is not None
                  else np.zeros(c_phys))
        if timings is not None:
            timings["host_data_s"] = round(t_data, 3)
            timings["dispatch_s"] = round(t_dispatch, 3)
            timings["device_sync_s"] = round(time.perf_counter() - t3, 3)
        return params_c, opt_c, stats_c, loss_h, consumed

    def train_cluster_resident(self, plan: ClusterPlan, params, stats, *,
                               round_idx: int = 0, epochs: int = 1,
                               lr: float | None = None,
                               sync_all_later_stages: bool = False):
        """Device-resident FedAvg round: params/optimizer/stats stay on
        the mesh between rounds and the round barrier is the on-mesh
        weighted ``fedavg_psum`` (:func:`make_fedavg_step`) — no
        per-round host restack/upload/pull of the full model, which on a
        tunneled chip dominates round wall-clock.  Numerically identical
        to the host fold: stage-1 columns enter the weighted mean with
        their own ``data_count``; sync-grouped later-stage columns hold
        identical shards whose weights sum to the group weight.

        Returns ``None`` when this plan needs the general host path
        (parallel axes, LoRA, column chunking); otherwise a
        ``RoundOutcome``-shaped namespace ``(params, stats, num_samples,
        ok)`` whose trees are device-resident (checkpointing pulls them
        once; ``validate`` consumes them in place).  Reuse across rounds
        keys on the IDENTITY of the params tree returned last round — a
        rollback or NaN skip in the round loop passes a different tree
        and transparently rebuilds from host.
        """
        import types

        par = self._parallel_axis()
        if par is not None and not plan.cuts:
            return None  # axes-path steps have no resident equivalent
        if self.cfg.learning.lora_rank > 0:
            return None
        stage1 = plan.stage1_clients
        if not stage1:
            return None
        c_phys, s_phys, cuts_phys, tp, sp, ep = self._geometry(
            plan, len(stage1))
        if len(stage1) > c_phys:
            return None  # column chunking: host path interleaves chunks
        counts = {c: plan.label_counts[plan.stage1_clients.index(c)]
                  for c in stage1}
        client_sync, sync_key = self._sync_map(
            plan, c_phys, len(stage1), sync_all_later_stages)
        mesh, pipe, optimizer, step = self._compiled(
            plan, c_phys, s_phys, cuts_phys, lr, sync_key, client_sync,
            tp=tp, sp=sp, ep=ep)
        M, mb = pipe.num_microbatches, pipe.mb_size

        key = (plan.cluster_id, c_phys, s_phys, tuple(cuts_phys), lr,
               sync_key, epochs, tp, sp, ep)
        cache = getattr(self, "_resident", None)
        if (cache is not None and cache["key"] == key
                and cache["token"] == id(params)):
            params_c, stats_c = cache["params_c"], cache["stats_c"]
            opt_init, fedavg, strip = (cache["opt_init"],
                                       cache["fedavg"], cache["strip"])
        else:
            from split_learning_tpu.parallel.pipeline import (
                make_fedavg_step,
            )
            params_c = shard_to_mesh(stack_for_clients(params, c_phys),
                                     mesh)
            stats_c = shard_to_mesh(stack_for_clients(stats, c_phys),
                                    mesh)

            def _opt_init(p_c):
                p0 = jax.tree_util.tree_map(lambda a: a[0], p_c)
                return stack_for_clients(optimizer.init(p0), c_phys)

            opt_init = jax.jit(_opt_init)
            fedavg = make_fedavg_step(mesh)
            strip = jax.jit(
                lambda t: jax.tree_util.tree_map(lambda a: a[0], t))
            old = getattr(self, "_resident", None)
            cache = {"key": key, "opt_init": opt_init, "fedavg": fedavg,
                     "strip": strip}
            # lr decay changes the cache key every decay round (lr is
            # key[4]); carried moments must survive an lr-ONLY change —
            # the state is structurally identical, and resetting it on
            # decay boundaries would reintroduce the Adam re-warmup
            # sawtooth on exactly the runs that decay
            if (self.cfg.learning.opt_resident and old is not None
                    and old.get("token") == id(params)
                    and "opt_c" in old
                    and old["key"][:4] == key[:4]
                    and old["key"][5:] == key[5:]):
                cache["opt_c"] = old["opt_c"]
        # fresh optimizer state every round — the host path's semantics
        # (optimizer.init per round); built ON DEVICE from the resident
        # params, no host zeros upload.  With learning.opt-resident the
        # PREVIOUS round's final state is reused instead (adaptive
        # moments keep their estimates across the FedAvg barrier —
        # kills the per-round Adam re-warmup sawtooth); a cache miss
        # (re-plan, rollback, first round) still starts fresh.
        place_opt = getattr(optimizer, "shard_opt_to_mesh",
                            shard_to_mesh)
        prev_opt = cache.get("opt_c")
        if self.cfg.learning.opt_resident and prev_opt is not None:
            opt_c = prev_opt
        else:
            opt_c = place_opt(opt_init(params_c), mesh)

        timings: dict = {}
        loaders = [self._loader(c, counts[c], round_idx)
                   for c in stage1]
        params_c, opt_c, stats_c, loss_h, consumed = self._drive_columns(
            step, loaders, c_phys, M, mb, epochs, round_idx,
            params_c, opt_c, stats_c, timings=timings)

        if not np.all(np.isfinite(loss_h)):
            # reference: any diverged client fails the whole round
            # (src/Server.py:162-166); resident state is now garbage
            self._resident = None
            return types.SimpleNamespace(params=params, stats=stats,
                                         num_samples=0, ok=False)

        t0 = time.perf_counter()
        weights = jnp.asarray(np.maximum(consumed, 1).astype(np.float32))
        avg_params_c = fedavg(params_c, weights)
        avg_stats_c = fedavg(stats_c, weights)
        ret_params = strip(avg_params_c)
        ret_stats = strip(avg_stats_c)
        timings["fedavg_dispatch_s"] = round(time.perf_counter() - t0, 3)
        cache.update(params_c=avg_params_c, stats_c=avg_stats_c,
                     token=id(ret_params), ret=(ret_params, ret_stats))
        if self.cfg.learning.opt_resident:
            # only keep the state alive on device when it will be
            # reused — for the default per-round re-init this would be
            # a dead ~2x-params Adam tree squatting in HBM
            cache["opt_c"] = opt_c
        else:
            cache.pop("opt_c", None)
        self._resident = cache
        return types.SimpleNamespace(params=ret_params, stats=ret_stats,
                                     num_samples=int(consumed.sum()),
                                     ok=True, timings=timings)

    def train_cluster(self, plan: ClusterPlan, params, stats, *,
                      round_idx: int = 0, epochs: int = 1,
                      client_subset: list | None = None,
                      per_client_params: dict | None = None,
                      lr: float | None = None,
                      sync_all_later_stages: bool = False,
                      send_params: bool = True,
                      send_weights: bool | dict = True) -> list[Update]:
        # send_params/send_weights are FLEX wire-economy knobs: in-process
        # columns have no wire, so "uploads" are free views and both flags
        # are no-ops here (ProtocolContext honors them)
        del send_params, send_weights
        stage1 = [c for c in plan.stage1_clients
                  if client_subset is None or c in client_subset]
        if not stage1:
            return []
        counts = {c: plan.label_counts[plan.stage1_clients.index(c)]
                  for c in stage1}
        c_phys, s_phys, cuts_phys, tp, sp, ep = self._geometry(
            plan, len(stage1))
        updates: list[Update] = []
        n_chunks = math.ceil(len(stage1) / c_phys)
        for chunk_i in range(n_chunks):
            chunk = stage1[chunk_i * c_phys:(chunk_i + 1) * c_phys]
            pad = c_phys - len(chunk)
            if (self._parallel_axis() is not None and tp == 1
                    and sp == 1 and ep == 1):
                # axes path: columns train independently (no grouped
                # gradient means); shared later stages meet at FedAvg
                client_sync, sync_key = None, ()
            else:
                client_sync, sync_key = self._sync_map(
                    plan, c_phys, len(chunk), sync_all_later_stages)
            mesh, pipe, optimizer, step = self._compiled(
                plan, c_phys, s_phys, cuts_phys, lr, sync_key,
                client_sync, tp=tp, sp=sp, ep=ep)
            M, mb = pipe.num_microbatches, pipe.mb_size
            cols = chunk + [chunk[-1]] * pad  # padded columns ignored below
            trees = [
                (per_client_params or {}).get(c, params) for c in cols
            ]
            def stack(ts):
                return jax.tree_util.tree_map(
                    lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                    *ts)

            use_lora = self.cfg.learning.lora_rank > 0
            frozen_c = None
            if use_lora:
                parts = [self._lora_partition(t) for t in trees]
                frozen_c = stack([f for f, _ in parts])
                params_c = stack([t for _, t in parts])
            else:
                params_c = stack(trees)
            opt0 = optimizer.init(
                jax.tree_util.tree_map(lambda a: a[0], params_c))
            opt_c = stack_for_clients(opt0, c_phys)
            stats_c = stack_for_clients(stats, c_phys)
            place_opt = getattr(optimizer, "shard_opt_to_mesh",
                                shard_to_mesh)
            opt_c = place_opt(opt_c, mesh)
            params_c, stats_c = (shard_to_mesh(t, mesh)
                                 for t in (params_c, stats_c))
            if frozen_c is not None:
                frozen_c = shard_to_mesh(frozen_c, mesh)

            loaders = [self._loader(c, counts[c], round_idx)
                       for c in cols]
            params_c, opt_c, stats_c, loss_h, consumed = (
                self._drive_columns(
                    step, loaders, c_phys, M, mb, epochs, round_idx,
                    params_c, opt_c, stats_c, frozen_c=frozen_c))
            if use_lora:
                # bake adapters into dense weights per column before shard
                # extraction (merge_and_unload parity)
                from split_learning_tpu.ops.lora import lora_merge
                lrn = self.cfg.learning
                params_c = jax.vmap(
                    lambda f, t: lora_merge(
                        {**f, **t["head"]}, t["lora"],
                        alpha=lrn.lora_alpha, rank=lrn.lora_rank)
                )(frozen_c, params_c)
            params_h = jax.tree_util.tree_map(np.asarray, params_c)
            stats_h = jax.tree_util.tree_map(np.asarray, stats_c)
            updates.extend(self._extract_updates(
                plan, chunk, cols, params_h, stats_h, loss_h, consumed,
                client_sync))
        return updates

    def _extract_updates(self, plan: ClusterPlan, chunk, cols, params_h,
                         stats_h, loss_h, consumed, client_sync):
        """Per-(logical client, stage) shard updates from trained columns."""
        ranges = stage_ranges(len(self.specs), plan.cuts)
        col_tree = lambda tree, i: jax.tree_util.tree_map(  # noqa: E731
            lambda a: a[i], tree)
        out: list[Update] = []
        # stage 1: one update per real (non-padded) column
        a, b = ranges[0]
        for i, cid in enumerate(chunk):
            ok = bool(np.isfinite(loss_h[i]))
            out.append(Update(
                client_id=cid, stage=1, cluster=plan.cluster_id,
                params=shard_params(col_tree(params_h, i), self.specs, a, b),
                batch_stats=shard_params(col_tree(stats_h, i), self.specs,
                                         a, b),
                num_samples=int(consumed[i]), ok=ok))
        # later stages: one update per sync group.  Columns in a group
        # hold identical shard PARAMS by construction (grouped gradient
        # sync); their batch STATS diverge (each column normalizes its
        # own batches), so the group's stats are their consumed-weighted
        # mean — the closest emulation of the reference's one shared
        # later-stage client seeing every feeder's batches
        # (src/train/VGG16.py:154), and the same fold the on-mesh
        # resident path computes.
        from split_learning_tpu.ops.fedavg import fedavg_trees
        for s in range(2, len(ranges) + 1):
            a, b = ranges[s - 1]
            layer_names = [sp.name for sp in self.specs[a:b] if sp.make]
            groups = None
            if client_sync and layer_names:
                groups = client_sync.get(layer_names[0])
            if groups is None:
                groups = [[i] for i in range(len(cols))]
            logical = plan.clients[s - 1] or [f"_stage{s}"]
            for gi, grp in enumerate(groups):
                real = [i for i in grp if i < len(chunk)]
                if not real:
                    continue
                rep = real[0]
                cid = logical[min(gi, len(logical) - 1)]
                ok = bool(np.all(np.isfinite(loss_h[real])))
                group_stats = shard_params(col_tree(stats_h, rep),
                                           self.specs, a, b)
                if group_stats and len(real) > 1:
                    group_stats = fedavg_trees(
                        [shard_params(col_tree(stats_h, i), self.specs,
                                      a, b) for i in real],
                        [max(1, int(consumed[i])) for i in real])
                out.append(Update(
                    client_id=cid, stage=s, cluster=plan.cluster_id,
                    params=shard_params(col_tree(params_h, rep),
                                        self.specs, a, b),
                    batch_stats=group_stats,
                    num_samples=int(consumed[real].sum()), ok=ok))
        return out

    def validate(self, params, stats) -> ValResult:
        variables = {"params": params}
        if stats:
            variables["batch_stats"] = stats
        # loader + jitted eval step are cached on the context: validation
        # runs every round and must not re-load data or re-trace
        if not hasattr(self, "_val_cache"):
            from split_learning_tpu.data import make_data_loader
            from split_learning_tpu.runtime.validation import make_eval_step
            model = build_model(self.cfg.model_key, **self.model_kwargs)
            loader = make_data_loader(
                self.dataset, self.cfg.val_batch_size, train=False,
                synthetic_size=self.cfg.synthetic_size,
                dataset_kwargs=self.dataset_kwargs)
            self._val_cache = (loader, make_eval_step(model, bool(stats)))
        loader, step = self._val_cache
        total_loss, total_correct, n = 0.0, 0, 0
        for i, (x, labels) in enumerate(loader):
            if (self.cfg.val_max_batches is not None
                    and i >= self.cfg.val_max_batches):
                break
            loss, correct = step(variables, jnp.asarray(x),
                                 jnp.asarray(labels))
            total_loss += float(loss)
            total_correct += int(correct)
            n += int(np.asarray(labels).size)  # token-level for LM labels
        return ValResult(loss=total_loss / max(n, 1),
                         accuracy=total_correct / max(n, 1), num_samples=n)
