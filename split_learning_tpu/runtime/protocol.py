"""Typed control-plane protocol.

The reference's wire vocabulary is untyped dicts with an ``action`` key
pushed through RabbitMQ (client→server REGISTER ``client.py:57``, NOTIFY
``src/train/VGG16.py:121-126``, UPDATE ``src/RpcClient.py:128-132``;
server→client START ``src/Server.py:262-272``, SYN ``:293-296``, PAUSE
``:140-153``, STOP ``:276-287``).  Here every message is a dataclass; a
READY ack is added so the server's 25-second settle sleep
(``src/Server.py:289`` — a time-based barrier papering over a race,
SURVEY.md §5.2) becomes an explicit barrier, and a HEARTBEAT frame
(no reference equivalent — its failure model is "hang forever",
SURVEY.md §5.3) carries each client's live telemetry snapshot to the
server's fleet monitor (``runtime/telemetry.py``).

Queue naming keeps the reference topology so the protocol surface maps
1:1 (SURVEY.md §1 L0 table):

* ``rpc_queue``                              any client → server
* ``reply_{client_id}``                      server → one client
* ``intermediate_queue_{stage}_{cluster}``   stage k → k+1 activations
  (shared per cluster — natural load balance across same-stage clients)
* ``gradient_queue_{stage}_{client_id}``     stage k+1 → one stage-k client
"""

from __future__ import annotations

import collections
import dataclasses
import io
import math
import os
import pickle
import struct
import uuid
import zlib
from typing import Any

import numpy as np

try:                                   # bf16 wire payloads (jax dep)
    import ml_dtypes as _ml_dtypes
    _BF16 = np.dtype(_ml_dtypes.bfloat16)
except ImportError:                    # pragma: no cover - jax ships it
    _BF16 = None

RPC_QUEUE = "rpc_queue"


def reply_queue(client_id: str) -> str:
    return f"reply_{client_id}"


def intermediate_queue(stage: int, cluster: int,
                       pair: int | None = None) -> str:
    """Forward-activation queue.  ``pair`` selects 2LS's fixed 1:1
    edge<->head pairing (``intermediate_queue_{layer}_{idx}``,
    ``other/2LS/src/train/VGG16.py:23``) instead of the shared
    per-cluster queue's natural load balancing."""
    base = f"intermediate_queue_{stage}_{cluster}"
    return base if pair is None else f"{base}_p{pair}"


def gradient_queue(stage: int, client_id: str) -> str:
    return f"gradient_queue_{stage}_{client_id}"


def aggregate_queue(cluster: int, group: int) -> str:
    """Aggregator-tree upload queue (``aggregation.fan-in``): the
    clients of L1 group ``group`` publish their round UPDATE here
    instead of ``rpc_queue``; the group's
    :class:`~split_learning_tpu.runtime.aggregate.L1Aggregator` folds
    them into one :class:`PartialAggregate` for the root."""
    return f"aggregate_queue_{cluster}_{group}"


def digest_queue(node_id: str) -> str:
    """Heartbeat roll-up queue (``observability.digest-interval``):
    clients assigned to aggregator node ``node_id`` publish their
    HEARTBEAT frames here instead of ``rpc_queue``; the node's digest
    worker folds them into one :class:`FleetDigest` per interval, so
    the server's rpc ingest is O(nodes), not O(clients)."""
    return f"digest_queue_{node_id}"


# --------------------------------------------------------------------------
# control messages
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Register:
    """client → server: join the round (with the offline profile)."""
    client_id: str
    stage: int                      # 1-based stage index ("layer_id")
    cluster: int | None = None      # manual cluster assignment, or None
    profile: dict | None = None     # {exe_time, size_data, speed, network}


@dataclasses.dataclass
class Ready:
    """client → server: shard built, data loaded — replaces sleep(25).

    ``round_idx`` carries the START's generation: a late READY from an
    invocation the server already gave up on must not count toward a
    newer invocation's READY barrier (the server would then SYN a client
    that is still unwinding the old round)."""
    client_id: str
    round_idx: int = 0


@dataclasses.dataclass
class Notify:
    """stage-1 client → server: local data exhausted this round.

    ``round_idx`` fences the barrier: a straggler's NOTIFY from a round
    the server already dropped must not satisfy a later round's barrier."""
    client_id: str
    cluster: int
    round_idx: int = 0


@dataclasses.dataclass
class Update:
    """client → server: round's trained shard parameters.

    ``round_idx`` fences aggregation: without it, a straggler dropped in
    round N that wakes during round N+1 would have its stale round-N
    weights counted as N+1's contribution."""
    client_id: str
    stage: int
    cluster: int
    params: Any                     # pytree of np arrays (host-side)
    num_samples: int                # FedAvg weight (data_count semantics)
    ok: bool = True                 # False -> NaN seen, skip aggregation
    batch_stats: Any | None = None  # shard's running stats (BN models)
    round_idx: int = 0
    # delta-encoded Update (transport.codec rpc family): params holds
    # ``trained - base`` against the server's versioned shadow copy of
    # what it sent in START.  None = full frame (the resync fallback
    # whenever the version chain broke: client restart, shadow loss).
    delta_base: int | None = None
    # async mode (learning.mode: async): the server generation this
    # client's params were SEEDED from — rides the existing delta-base
    # advertisement chain (START extra carries the gen, the client
    # stamps it back).  The server's bounded-staleness admission window
    # folds ``server_version - version <= learning.max-staleness`` with
    # staleness-scaled weight and rejects-and-counts the rest.  None =
    # sync client (round_idx carries the same fence).
    version: int | None = None
    # piggybacked TelemetrySnapshot dict (runtime/telemetry.py): every
    # sync round delivers one fleet sample for free, heartbeat thread
    # or not.  A plain dict, NOT the dataclass — the restricted
    # unpickler's vocabulary stays closed.
    telemetry: dict | None = None


@dataclasses.dataclass
class Start:
    """server → client: round config + shard weights."""
    start_layer: int
    end_layer: int                  # -1 = to the end
    cluster: int
    params: Any                     # shard pytree (np arrays)
    batch_stats: Any | None = None
    learning: dict | None = None    # lr/momentum/... overrides
    label_counts: Any | None = None  # stage-1: per-label sample counts
    round_idx: int = 0
    extra: dict | None = None       # strategy-specific knobs (sda_size, ...)


@dataclasses.dataclass
class Syn:
    """server → client: begin training.

    ``sda_fence_quorum`` / ``sda_feeders``, when set, override the
    static values sent in START: the server recomputes them from the
    RESPONSIVE client set after the READY barrier, so a previous-stage
    client dropped mid-round (whose fence copies will never arrive)
    can't leave the strict-SDA drain waiting on a quorum that can no
    longer be met (ADVICE round 5)."""
    round_idx: int = 0
    sda_fence_quorum: int | None = None
    sda_feeders: list | None = None


@dataclasses.dataclass
class Pause:
    """server → client: stop the hot loop, upload weights.

    ``send_weights=False`` is FLEX's non-aggregation-round PAUSE
    (``other/FLEX/src/Server.py:140-143``)."""
    send_weights: bool = True


@dataclasses.dataclass
class Stop:
    """server → client: terminate."""
    reason: str = ""


@dataclasses.dataclass
class PartialAggregate:
    """Aggregator → its parent (rpc queue at the root, the parent
    group's aggregate queue below it): one aggregator-tree group's
    folded contribution (``aggregation.fan-in`` /
    ``aggregation.levels``, ``runtime/aggregate.py``).  Carries the
    group's per-path weighted **sums** (f32, NOT averaged — every
    interior level continues the running fold and the root divides
    once, so tree depth never changes how many divides touch the
    data).  ``members`` is the per-client metadata the root needs for
    barrier bookkeeping and fleet telemetry (client_id, stage,
    num_samples, ok, telemetry) — the clients behind an aggregator
    still count individually everywhere except the fold itself; an L2
    node concatenates its children's member lists.  ``round_idx``
    carries the server's invocation generation, same fence as Update.

    ``codec``/``codec_base`` describe a compressed payload
    (``transport.codec: {partial: ...}``, ``runtime/codec/partial.py``):
    ``sums`` then holds tiled-int8 :class:`QuantLeaf` codes of the
    group **mean** (optionally delta'd against the generation
    ``codec_base`` START shard both endpoints hold), and the receiver
    reconstructs f32 sums before folding.  None = raw f32 sums — the
    bit-parity leg."""
    aggregator_id: str
    cluster: int
    group: int                      # group index (canonical position)
    stage: int                      # the one stage this group covers
    round_idx: int = 0
    sums: Any = None                # pytree of f32 weighted sums
    weight: float = 0.0             # total fold weight behind the sums
    dtypes: Any = None              # pytree of original dtype strings
    stat_sums: Any = None           # batch-stats sums (BN models)
    stat_weight: float = 0.0
    stat_dtypes: Any = None
    n_samples: int = 0              # stage-1 samples folded (0 otherwise)
    members: list | None = None     # per-client {client_id, stage, ...}
    level: int = 1                  # tree level that produced this
    codec: str | None = None        # partial codec spec, None = raw f32
    codec_base: int | None = None   # delta base generation, None = plain
    # packed members (codec path only): at 10k clients the per-client
    # member dicts dominate a root partial's bytes — zlib'd pickle
    # (pack_members/unpack_members, ~10x on the repetitive id/key
    # text) keeps the root ingress flat.  Exclusive with ``members``;
    # decode_partial_msg restores the plain list.
    members_z: bytes | None = None


def pack_members(members: list | None) -> bytes | None:
    """crc32-prefixed zlib'd pickle of a PartialAggregate member list
    (the codec'd wire form — see ``PartialAggregate.members_z``)."""
    if not members:
        return None
    body = zlib.compress(
        pickle.dumps(members, protocol=pickle.HIGHEST_PROTOCOL), 6)
    return struct.pack(">I", zlib.crc32(body)) + body


def unpack_members(blob: bytes) -> list:
    """Inverse of :func:`pack_members`: own crc checked BEFORE any
    decompression/unpickling (the outer frame crc already covered
    these bytes, but the blob also crosses aggregator levels — same
    integrity-first discipline as every frame family), then the
    restricted unpickler (member dicts are plain builtins; anything
    else in the blob is rejected like any hostile frame payload)."""
    if len(blob) < 4:
        raise CorruptFrame("packed member list truncated")
    (want,) = struct.unpack_from(">I", blob, 0)
    body = blob[4:]
    if zlib.crc32(body) != want:
        raise CorruptFrame("packed member list checksum mismatch")
    out = _SafeUnpickler(io.BytesIO(zlib.decompress(body))).load()
    if not isinstance(out, list):
        raise CorruptFrame(
            f"packed member list decoded to {type(out).__name__}")
    return out


@dataclasses.dataclass
class AggHello:
    """aggregator node → server (rpc queue): a standalone aggregator
    process announcing itself for adoption (``aggregation.remote``).
    Re-sent on reconnect; liveness afterwards rides the node's
    HEARTBEAT frames like any client's."""
    node_id: str
    capacity: int = 0               # informational (groups it can take)


@dataclasses.dataclass
class AggAssign:
    """server → one aggregator node (its reply queue): the node's
    group assignment for one train_cluster invocation.  ``groups`` is
    a list of plain dicts ``{idx, stage, level, members, parent}``
    (members are client ids at level 1, child group keys above;
    ``parent`` is the parent group's index, None = publish to the
    root's rpc queue).  ``bases`` carries the per-stage START shard
    trees when the partial codec is delta-encoded — both endpoints
    must hold the same base."""
    node_id: str
    cluster: int
    gen: int                        # invocation generation fence
    round_idx: int = 0
    groups: list | None = None
    deadline_s: float = 600.0       # forced-flush deadline from receipt
    codec: str | None = None        # partial codec spec for publishes
    bases: Any = None               # {stage: tree} delta bases
    chunk_bytes: int | None = None  # partial chunking cap


@dataclasses.dataclass
class AggFlush:
    """server → one aggregator node: flush every still-incomplete
    group of generation ``gen`` now (the server gave up waiting on the
    group's stragglers)."""
    node_id: str = ""
    gen: int = 0


@dataclasses.dataclass
class StageHello:
    """stage host → server (rpc queue): a standalone pipeline stage
    host announcing itself for adoption (``pipeline.remote``,
    ``runtime/stagehost.py``).  Re-sent until adopted (an assignment
    arrives); liveness afterwards rides the host's HEARTBEAT frames
    like any client's.  ``capacity`` is informational — how many
    later-stage client slots the host is willing to run."""
    host_id: str
    capacity: int = 0


@dataclasses.dataclass
class StageAssign:
    """server → one stage host (its reply queue): the later-stage
    client slots the host must run.  ``slots`` is a list of plain
    dicts ``{client_id, stage, cluster}`` — the host spins one inner
    protocol client per slot, which REGISTERs under the assigned
    ``client_id`` and then speaks the ordinary choreography (so the
    whole transport/chaos/codec stack composes unchanged).  ``gen``
    carries the server's invocation generation on MID-ROUND
    re-assignment (stage-host death fallback): a re-assigned slot
    reuses the dead host's ``client_id``, so the ShardRunner seed —
    and therefore the fold — is bit-identical to the fault-free
    round."""
    host_id: str
    gen: int = 0
    round_idx: int = 0
    slots: list | None = None


@dataclasses.dataclass
class FleetDigest:
    """aggregator node → server (rpc queue), every
    ``observability.digest-interval`` seconds: one merged health
    summary of the clients whose heartbeats the node consumes from its
    :func:`digest_queue` — exact per-state counts and counter sums,
    log-bucket rate/compute-rate quantile sketches, per-stage step
    stats, the top-K worst stragglers with their last snapshots, and
    the state transitions since the previous digest
    (``runtime/sketch.py``).  ``digest['t']``/``digest['seq']`` are
    the server's staleness guard, same contract as a Heartbeat's: a
    duplicated or reordered digest is rejected-and-counted
    (``stale_digests``), never double-folded.  A plain dict — the
    restricted unpickler's vocabulary stays closed."""
    node_id: str
    round_idx: int = 0
    digest: dict | None = None


@dataclasses.dataclass
class DigestRoute:
    """server → one client (its reply queue): re-point the client's
    heartbeat publishes.  ``queue`` names a :func:`digest_queue`
    (roll up through that aggregator node) or is None (beat directly
    on the rpc queue — the fallback when the client's digest node
    died).  The initial route rides START ``extra['digest']`` so the
    common path costs no extra frame; this message exists for the
    MID-ROUND fallback, where waiting for the next START would leave
    the client beating into a dead node's queue."""
    client_id: str
    queue: str | None = None


@dataclasses.dataclass
class BlackboxDump:
    """server → one participant (its reply queue): flush your flight
    recorder NOW (``runtime/blackbox.py``).  Fanned out to every live
    client / aggregator node / stage host when the FleetMonitor marks
    any participant ``lost`` or a child process exits, so one death
    snapshots the whole fleet's last N seconds of ring events — the
    inputs ``tools/sl_postmortem.py`` assembles into a causal
    root-cause report.  Lifecycle-orthogonal (like Heartbeat): legal
    in every protocol state, consumed by the participants' control
    pumps without touching the round FSM.  ``reason`` names the
    trigger (e.g. ``lost:client_2_1``); ``t_req`` is the server's send
    clock, recorded into each dump so the assembler can align the
    snapshot edge across processes."""
    participant: str
    reason: str = ""
    t_req: float = 0.0


@dataclasses.dataclass
class Heartbeat:
    """client → server, on the rpc queue, from a background thread at
    ``observability.heartbeat-interval``: liveness + a full
    :class:`~split_learning_tpu.runtime.telemetry.TelemetrySnapshot`
    as a plain dict (counters, gauges, histogram digests, current
    round, EWMA samples/s).  The snapshot's monotonic ``seq`` and
    sender clock ``t`` are the server's staleness guard: a duplicated
    or reordered heartbeat must never flap a ``lost`` client back to
    life.  Deliberately small and pickled (SLT1) — it shares the rpc
    queue with UPDATE uploads and must cost ~nothing."""
    client_id: str
    round_idx: int = 0
    telemetry: dict | None = None


# --------------------------------------------------------------------------
# data-plane messages
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Activation:
    """stage k → stage k+1. ``trace`` is the routing stack of client_ids,
    appended per forward hop, popped per backward hop
    (``src/train/VGG16.py:24-31``, ``:41-43``).  ``round_idx`` fences
    rounds: a consumer drops messages stamped with a different round, so
    activations published into a round the server already dropped (elastic
    mid-round PAUSE) can't leak into the next round's batches — the
    reference has no such fence because its queues only ever carry one
    round at a time (it hangs instead of dropping rounds, SURVEY.md §5.3)."""
    data_id: str
    data: Any          # ndarray, or a pytree of ndarrays for models whose
    labels: np.ndarray  # stage boundaries carry extras (e.g. BERT's mask)
    trace: list
    cluster: int
    round_idx: int = 0


@dataclasses.dataclass
class Gradient:
    """stage k+1 → the originating stage-k client."""
    data_id: str
    data: Any   # cotangent, same pytree structure as the Activation.data
    trace: list
    round_idx: int = 0


@dataclasses.dataclass
class EpochEnd:
    """stage k → stage k+1 (strict-SDA only): the feeder has dispatched
    its last batch of this epoch.  DCSL's hard ``sda_size`` window
    drains its leftovers only at epoch end
    (``other/DCSL/src/Scheduler.py:152-191`` processes full windows,
    then the epoch boundary clears the queues); this marker is how the
    head learns the boundary without the server round-trip.  Rides the
    data-plane queues so per-queue FIFO ordering guarantees it arrives
    AFTER every activation it fences.

    In >2-stage plans middle stages PROPAGATE the marker to every
    downstream queue, but only once the full previous-stage quorum of
    copies has arrived (``sda_fence_quorum``): a receiver hears one
    copy per previous-stage device, and only the LAST copy proves —
    via per-queue FIFO — that every activation the fence covers has
    arrived, whichever previous-stage device relayed it."""
    client_id: str
    round_idx: int = 0
    epoch: int = 0


@dataclasses.dataclass
class QuantLeaf:
    """One absmax-quantized float tensor on the data-plane wire:
    ``x ≈ q * scale``.  Deliberately NOT a registered pytree so
    tree_maps over a wire payload treat it as a leaf.

    Two generations share this class:

    * legacy per-tensor form (``transport.wire-dtype: int8``,
      ``src/train/VGG16.py:27`` fp32-pickle contrast): ``q`` int8 with
      the tensor's own shape, ``scale`` a python float
      (``max|x| / 127``), defaults for the rest;
    * tiled codec form (``transport.codec`` quantizers,
      ``runtime/codec/quant.py``): ``q`` is the FLAT padded code array
      — int8 codes, or uint8 with two 4-bit codes per byte when
      ``bits == 4`` — ``scale`` a float32 array with one entry per
      ``tile`` elements, and ``shape`` the original tensor shape.  A
      non-finite payload tile ships a NaN scale so the receiver's NaN
      sentinel still fires after dequantization.
    """
    q: np.ndarray            # codes (see above)
    scale: Any               # float, or float32 ndarray of tile scales
    bits: int = 8            # 8 = one code per byte, 4 = packed pairs
    tile: int = 0            # elements per scale; 0 = per-tensor scalar
    shape: tuple | None = None   # original shape (tiled form only)


@dataclasses.dataclass
class SparseLeaf:
    """One top-k sparsified float tensor on the data-plane wire
    (``transport.codec`` ``topk:<frac>``, ``runtime/codec/sparse.py``):
    flat ``idx`` into the dense tensor, the kept ``val``ues, and the
    dense ``shape`` to scatter back into (zeros elsewhere).  The
    sender's error-feedback residual holds what was not sent.  Like
    QuantLeaf, deliberately NOT a registered pytree."""
    idx: np.ndarray          # int32 flat indices, sorted ascending
    val: np.ndarray          # float32 values at idx
    shape: tuple = ()        # dense shape


@dataclasses.dataclass
class _TensorRef:
    """Placeholder left in a TENSOR frame's pickled skeleton where an
    ndarray leaf was lifted out into the raw out-of-band blob table
    (index into it).  Wire-internal only — never a top-level message."""
    idx: int


CONTROL_TYPES = (Register, Ready, Notify, Update, Start, Syn, Pause,
                 Stop, Heartbeat, PartialAggregate, AggHello, AggAssign,
                 AggFlush, FleetDigest, DigestRoute, StageHello,
                 StageAssign, BlackboxDump)
DATA_TYPES = (Activation, Gradient, EpochEnd)
#: messages whose ndarray payloads ride the zero-copy TENSOR framing
#: (the high-volume data plane + the round's weight uploads — Update
#: and the aggregator tree's PartialAggregate); control messages keep
#: the pickled frame — their payloads are small and their schema
#: churns more
TENSOR_TYPES = (Activation, Gradient, Update, PartialAggregate)
_TYPE_BY_NAME = {t.__name__: t for t in CONTROL_TYPES + DATA_TYPES}
#: nested wire-format helpers (never valid as a top-level message)
_WIRE_HELPERS = {"QuantLeaf": QuantLeaf, "SparseLeaf": SparseLeaf,
                 "_TensorRef": _TensorRef}


# --------------------------------------------------------------------------
# serialization
# --------------------------------------------------------------------------
# Three frame families, dispatched on a 4-byte magic:
#
# * ``SLT1`` — pickled frame: ``MAGIC | crc32(body) | pickle(body)``.
#   Control messages only; a restricted unpickler admits protocol
#   dataclasses + builtins, unlike the reference's bare pickle.loads of
#   broker bytes (SURVEY.md §1 L0).
# * ``SLT2`` — zero-copy TENSOR frame for the data plane
#   (Activation/Gradient/Update): every ndarray leaf is lifted out of
#   the message into a raw out-of-band blob with a fixed binary header
#   (dtype code, flags, shape, crc32, byte length) and decoded with
#   ``np.frombuffer`` straight off the received buffer — no pickle
#   byte-shuffling on the hot path, and the (tiny) pickled skeleton
#   holds only ``_TensorRef`` placeholders.  The meta region opens with
#   an OPTIONAL length-prefixed trace context (``runtime/spans.py``:
#   trace id, sender span id, send timestamp — 32 bytes when tracing,
#   0 otherwise) that links the sender's publish span to the
#   receiver's consume span; it is covered by the outer crc and
#   surfaced on the decoded message as ``msg._ctx`` (opaque bytes).
# * ``SLTC`` — chunk frame: a frame larger than the chunk cap is split
#   into crc'd parts (``encode_parts``) that a :class:`FrameAssembler`
#   reassembles, so one huge UPDATE can't trip the broker's frame cap.
#
# Every family is checksummed end to end: a corrupt or truncated frame
# raises :class:`CorruptFrame` BEFORE any unpickling or np.frombuffer —
# bit-rot on the wire (or an injected chaos fault) must never reach the
# unpickler, whose failure modes on garbage are arbitrary exceptions deep
# inside numpy reconstruction.  In the TENSOR frame the outer crc covers
# the headers + skeleton and each blob carries its OWN crc, so every
# byte is covered exactly once (no double hashing of bulk data).

FRAME_MAGIC = b"SLT1"
TENSOR_MAGIC = b"SLT2"
CHUNK_MAGIC = b"SLTC"
_HDR_LEN = len(FRAME_MAGIC) + 4


class CorruptFrame(pickle.UnpicklingError):
    """Frame failed the integrity check (bad magic / length / checksum).

    Subclasses UnpicklingError so callers guarding decode() with the
    pre-checksum except clause keep working."""


class _SafeUnpickler(pickle.Unpickler):
    _ALLOWED = {
        ("builtins", "dict"), ("builtins", "list"), ("builtins", "tuple"),
        ("builtins", "set"), ("builtins", "frozenset"),
        ("builtins", "complex"), ("builtins", "bytearray"),
        ("numpy", "dtype"), ("numpy", "ndarray"),
        ("ml_dtypes", "bfloat16"),  # compressed wire payloads
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.numeric", "_frombuffer"),
        ("numpy.core.numeric", "_frombuffer"),
    }

    def find_class(self, module, name):
        if module == "split_learning_tpu.runtime.protocol":
            if name in _TYPE_BY_NAME:
                return _TYPE_BY_NAME[name]
            if name in _WIRE_HELPERS:
                return _WIRE_HELPERS[name]
        if (module, name) in self._ALLOWED:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"disallowed class in protocol message: {module}.{name}")


def encode_pickled(msg) -> bytes:
    """Legacy pickled frame (``SLT1``) — still what control messages
    use, and kept callable on data messages so the fp32 wire-parity
    test can diff the two framings."""
    if type(msg).__name__ not in _TYPE_BY_NAME:
        raise TypeError(f"not a protocol message: {type(msg)!r}")
    body = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    return FRAME_MAGIC + struct.pack(">I", zlib.crc32(body)) + body


def _decode_pickled(raw: bytes):
    (want,) = struct.unpack_from(">I", raw, len(FRAME_MAGIC))
    body = raw[_HDR_LEN:]
    if zlib.crc32(body) != want:
        raise CorruptFrame("protocol frame checksum mismatch "
                           f"({len(raw)} bytes)")
    msg = _SafeUnpickler(io.BytesIO(body)).load()
    # wire helpers (QuantLeaf/_TensorRef) are only valid NESTED in a
    # payload — a bare one must fail here, not as an AttributeError in
    # a hot loop
    if not isinstance(msg, CONTROL_TYPES + DATA_TYPES):
        raise pickle.UnpicklingError(
            f"not a protocol message: {type(msg).__name__}")
    return msg


# -- TENSOR frames ----------------------------------------------------------

#: dtype code table — the fixed vocabulary of raw-blob payloads.  bf16
#: is a first-class code (the wire default for activations/gradients);
#: anything outside the table (object arrays, exotic dtypes) stays in
#: the pickled skeleton, which the restricted unpickler still guards.
_DTYPE_BY_CODE: dict[int, np.dtype] = {
    1: np.dtype(np.float32), 2: np.dtype(np.float64),
    3: np.dtype(np.float16), 5: np.dtype(np.int8),
    6: np.dtype(np.int16), 7: np.dtype(np.int32),
    8: np.dtype(np.int64), 9: np.dtype(np.uint8),
    10: np.dtype(np.uint16), 11: np.dtype(np.uint32),
    12: np.dtype(np.uint64), 13: np.dtype(np.bool_),
}
if _BF16 is not None:
    _DTYPE_BY_CODE[4] = _BF16
_CODE_BY_DTYPE = {dt: c for c, dt in _DTYPE_BY_CODE.items()}

#: per-tensor fixed header: dtype code, flags, ndim, crc32(raw bytes),
#: raw byte length — shape dims (u64 each) follow
_THDR = struct.Struct(">BBHIQ")
#: header ``flags`` bits, set on a QuantLeaf's code blob and
#: cross-checked against the pickled skeleton at decode time — a
#: skeleton/blob disagreement (bit rot the crc math happened to
#: forgive, or a crafted skeleton) is rejected as corrupt instead of
#: being mis-dequantized:
TENSOR_FLAG_PACKED4 = 0x01   # two 4-bit codes per byte (bits == 4)
TENSOR_FLAG_TILED = 0x02     # per-tile scales (tile > 0)
_MAX_NDIM = 32
_MAX_TENSORS = 1 << 20


def _blob(a: np.ndarray):
    """Contiguous little-endian buffer view of one array (no copy when
    the array already is one)."""
    a = np.ascontiguousarray(a)
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
    try:
        return a, memoryview(a).cast("B")
    except (TypeError, ValueError):   # dtype without buffer support
        return a, a.tobytes()


#: trace-context sanity cap: today's context is 32 bytes; the u8 cap
#: bounds what a corrupt length field can make the decoder slice
_MAX_CTX_BYTES = 255


def _encode_tensor(msg, ctx: bytes = b"") -> bytes:
    if len(ctx) > _MAX_CTX_BYTES:
        raise ValueError(f"trace context of {len(ctx)} bytes exceeds "
                         f"the {_MAX_CTX_BYTES}-byte cap")
    tensors: list = []
    tflags: list[int] = []

    def strip(o, flags: int = 0):
        if isinstance(o, np.ndarray) and o.dtype in _CODE_BY_DTYPE:
            tensors.append(o)
            tflags.append(flags)
            return _TensorRef(len(tensors) - 1)
        if isinstance(o, QuantLeaf):
            qf = ((TENSOR_FLAG_PACKED4 if o.bits == 4 else 0)
                  | (TENSOR_FLAG_TILED if o.tile else 0))
            return QuantLeaf(q=strip(o.q, qf), scale=strip(o.scale),
                             bits=o.bits, tile=o.tile, shape=o.shape)
        if isinstance(o, SparseLeaf):
            return SparseLeaf(idx=strip(o.idx), val=strip(o.val),
                              shape=o.shape)
        if isinstance(o, dict):
            return {k: strip(v) for k, v in o.items()}
        if isinstance(o, list):
            return [strip(v) for v in o]
        if isinstance(o, tuple):
            return tuple(strip(v) for v in o)
        return o

    skel = type(msg)(**{f.name: strip(getattr(msg, f.name))
                        for f in dataclasses.fields(msg)})
    skel_bytes = pickle.dumps(skel, protocol=pickle.HIGHEST_PROTOCOL)

    headers: list[bytes] = []
    blobs: list = []
    for a, fl in zip(tensors, tflags):
        a, buf = _blob(a)
        headers.append(
            _THDR.pack(_CODE_BY_DTYPE[a.dtype], fl, a.ndim,
                       zlib.crc32(buf), a.nbytes)
            + struct.pack(f">{a.ndim}Q", *a.shape))
        blobs.append(buf)
    meta = (struct.pack(">H", len(ctx)) + ctx
            + struct.pack(">I", len(tensors)) + b"".join(headers)
            + struct.pack(">I", len(skel_bytes)) + skel_bytes)
    return b"".join([TENSOR_MAGIC, struct.pack(">I", zlib.crc32(meta)),
                     meta, *blobs])


def _decode_tensor(raw: bytes):
    view = memoryview(raw)
    try:
        (want,) = struct.unpack_from(">I", raw, 4)
        off = 8
        (ctx_len,) = struct.unpack_from(">H", raw, off)
        off += 2
        if ctx_len > _MAX_CTX_BYTES or off + ctx_len > len(raw):
            raise CorruptFrame(f"tensor frame claims {ctx_len}-byte "
                               "trace context")
        ctx = raw[off:off + ctx_len]
        off += ctx_len
        (n_tensors,) = struct.unpack_from(">I", raw, off)
        off += 4
        if n_tensors > _MAX_TENSORS:
            raise CorruptFrame(f"tensor frame claims {n_tensors} tensors")
        hdrs = []
        for _ in range(n_tensors):
            code, flags, ndim, bcrc, nbytes = _THDR.unpack_from(raw, off)
            off += _THDR.size
            if ndim > _MAX_NDIM:
                raise CorruptFrame(f"tensor frame claims ndim={ndim}")
            shape = struct.unpack_from(f">{ndim}Q", raw, off)
            off += 8 * ndim
            hdrs.append((code, flags, shape, bcrc, nbytes))
        (skel_len,) = struct.unpack_from(">I", raw, off)
        off += 4
        if off + skel_len > len(raw):
            raise CorruptFrame("tensor frame skeleton truncated")
        skel = raw[off:off + skel_len]
        off += skel_len
    except struct.error as e:
        raise CorruptFrame(f"tensor frame header truncated: {e}") from None
    # integrity BEFORE np.frombuffer / unpickling: meta (headers +
    # skeleton) under the outer crc, each raw blob under its own
    if zlib.crc32(view[8:off]) != want:
        raise CorruptFrame("tensor frame meta checksum mismatch "
                           f"({len(raw)} bytes)")
    if len(raw) - off != sum(h[4] for h in hdrs):
        raise CorruptFrame("tensor frame blob region length mismatch")
    arrays = []
    flags_of: list[int] = []
    for code, flags, shape, bcrc, nbytes in hdrs:
        dt = _DTYPE_BY_CODE.get(code)
        if dt is None:
            raise CorruptFrame(f"unknown tensor dtype code {code}")
        count, rem = divmod(nbytes, dt.itemsize)
        if rem or math.prod(shape) != count:
            raise CorruptFrame("tensor header shape/length mismatch")
        if zlib.crc32(view[off:off + nbytes]) != bcrc:
            raise CorruptFrame("tensor blob checksum mismatch")
        arrays.append(np.frombuffer(raw, dtype=dt, count=count,
                                    offset=off).reshape(shape))
        flags_of.append(flags)
        off += nbytes
    msg = _SafeUnpickler(io.BytesIO(skel)).load()
    if not isinstance(msg, TENSOR_TYPES):
        raise pickle.UnpicklingError(
            f"not a tensor-frame message: {type(msg).__name__}")

    def fill(o):
        if isinstance(o, _TensorRef):
            if not 0 <= o.idx < len(arrays):
                raise CorruptFrame(f"tensor ref {o.idx} out of range")
            return arrays[o.idx]
        if isinstance(o, QuantLeaf):
            # the skeleton's quantizer parameters must agree with the
            # flags stamped on the code blob's header (both are under
            # the outer crc, but a crafted frame can lie in one place)
            if isinstance(o.q, _TensorRef) \
                    and 0 <= o.q.idx < len(flags_of):
                want = ((TENSOR_FLAG_PACKED4 if o.bits == 4 else 0)
                        | (TENSOR_FLAG_TILED if o.tile else 0))
                if flags_of[o.q.idx] != want:
                    raise CorruptFrame(
                        "quantized blob flags disagree with skeleton "
                        f"(header {flags_of[o.q.idx]:#x}, skeleton "
                        f"bits={o.bits} tile={o.tile})")
            return QuantLeaf(q=fill(o.q), scale=fill(o.scale),
                             bits=o.bits, tile=o.tile, shape=o.shape)
        if isinstance(o, SparseLeaf):
            idx, val = fill(o.idx), fill(o.val)
            # bounds-check HERE, where decode errors are caught and
            # counted (client._decode) — not at densify time on the
            # training thread, where an uncaught CorruptFrame would
            # kill the process a crafted frame should only cost one
            # message
            n = int(math.prod(o.shape)) if o.shape else 1
            if isinstance(idx, np.ndarray):
                if np.shape(idx) != np.shape(val):
                    raise CorruptFrame("sparse leaf idx/val length "
                                       "mismatch")
                if idx.size and (int(idx.min()) < 0
                                 or int(idx.max()) >= n):
                    raise CorruptFrame(
                        f"sparse leaf index out of range for shape "
                        f"{o.shape}")
            return SparseLeaf(idx=idx, val=val, shape=o.shape)
        if isinstance(o, dict):
            return {k: fill(v) for k, v in o.items()}
        if isinstance(o, list):
            return [fill(v) for v in o]
        if isinstance(o, tuple):
            return tuple(fill(v) for v in o)
        return o

    out = type(msg)(**{f.name: fill(getattr(msg, f.name))
                       for f in dataclasses.fields(msg)})
    if ctx_len:
        # opaque tracing sidecar, NOT a message field: consumers read it
        # via getattr so control frames (no attribute) degrade to None
        out._ctx = bytes(ctx)
    return out


def encode(msg, ctx: bytes = b"") -> bytes:
    """One complete frame: TENSOR framing for the data-plane payload
    types, the pickled frame for everything else.  ``ctx`` (an opaque
    trace context, ``runtime/spans.py``) rides the TENSOR meta header;
    the legacy pickled framing ignores it — SLT1 bytes stay bit-stable
    for the fp32 parity contract."""
    if type(msg).__name__ not in _TYPE_BY_NAME:
        raise TypeError(f"not a protocol message: {type(msg)!r}")
    if isinstance(msg, TENSOR_TYPES):
        return _encode_tensor(msg, ctx)
    return encode_pickled(msg)


def decode(raw: bytes):
    """Decode one COMPLETE frame (either framing).  Chunk frames only
    make sense inside a :class:`FrameAssembler`."""
    if len(raw) < _HDR_LEN:
        raise CorruptFrame(
            f"protocol frame missing magic/header ({len(raw)} bytes)")
    magic = raw[:4]
    if magic == TENSOR_MAGIC:
        return _decode_tensor(raw)
    if magic == CHUNK_MAGIC:
        raise CorruptFrame("chunk frame outside a FrameAssembler")
    if magic != FRAME_MAGIC:
        raise CorruptFrame(
            f"protocol frame missing magic/header ({len(raw)} bytes)")
    return _decode_pickled(raw)


# -- chunking ---------------------------------------------------------------

#: one frame's on-the-wire size cap before it is split into SLTC chunks
#: (config: ``transport.chunk-mb``).  Sized well under the broker's
#: 8 GiB frame sanity cap so a giant UPDATE can't kill the connection.
DEFAULT_CHUNK_BYTES = 512 << 20
_CHUNK_HDR = 16 + 8 + 2        # uuid | u32 idx | u32 total | u16 ctx-len
_MAX_CHUNKS = 1 << 16

#: assembled-frame sanity cap, the chunked twin of the broker's
#: per-frame cap (``runtime/bus.py MAX_FRAME_BYTES``): the broker
#: checks each frame's length prefix, but an SLTC-chunked message is
#: many legal frames whose ASSEMBLED size the broker never sees — a
#: corrupt/hostile chunk stream could drive an arbitrarily large
#: reassembly allocation.  Reassembly happens at the ENDPOINTS
#: (server/client/aggregator processes), so the operable knob is the
#: ``SLT_MAX_ASSEMBLED_GB`` env var set on each endpoint process —
#: the broker's ``--max-frame-gb`` cannot reach their
#: FrameAssemblers.  Exceeding the cap is a counted corrupt frame
#: (``oversize_frames``), not a process death.
try:
    MAX_ASSEMBLED_BYTES = int(
        float(os.environ.get("SLT_MAX_ASSEMBLED_GB", "8")) * (1 << 30))
except ValueError:
    MAX_ASSEMBLED_BYTES = 1 << 33


def encode_parts(msg, max_bytes: int | None = None,
                 ctx: bytes = b"") -> list[bytes]:
    """Encode into one or more publishable frames: a single complete
    frame when it fits ``max_bytes``, else crc'd SLTC chunks carrying a
    shared message id.  Per-queue FIFO (which every transport layer
    preserves, reliable included) is what keeps a message's chunks
    together; out-of-order arrival within the id is still handled.

    ``ctx`` (trace context) rides the inner TENSOR frame AND every
    chunk header, so a receiver can attribute chunk arrivals to the
    sender's publish span without waiting for reassembly."""
    if len(ctx) > _MAX_CTX_BYTES:
        raise ValueError(f"trace context of {len(ctx)} bytes exceeds "
                         f"the {_MAX_CTX_BYTES}-byte cap")
    frame = encode(msg, ctx)
    cap = int(max_bytes) if max_bytes else DEFAULT_CHUNK_BYTES
    if len(frame) <= cap:
        return [frame]
    mid = uuid.uuid4().bytes
    total = -(-len(frame) // cap)
    if total > _MAX_CHUNKS:
        raise ValueError(f"frame of {len(frame)} bytes needs {total} "
                         f"chunks (cap {_MAX_CHUNKS})")
    parts = []
    for idx in range(total):
        body = (mid + struct.pack(">II", idx, total)
                + struct.pack(">H", len(ctx)) + ctx
                + frame[idx * cap:(idx + 1) * cap])
        parts.append(CHUNK_MAGIC + struct.pack(">I", zlib.crc32(body))
                     + body)
    return parts


class FrameAssembler:
    """Per-consumer reassembly of SLTC chunk streams.

    ``feed`` returns the decoded message once complete (immediately for
    unchunked frames), or None while a chunked message is still
    partial.  Bounded: at most ``max_pending`` partial messages are
    held — on an at-most-once transport a dropped chunk strands its
    message, and the stalest partial is evicted rather than leaking.
    Not thread-safe: give each consumer thread its own assembler (same
    ownership rule as a transport connection).

    ``last_bytes`` holds the wire byte count of the most recently
    COMPLETED message (all its chunks for an SLTC stream) — how a
    consumer attributes ingress bytes to a decoded message without
    re-measuring the chunk stream."""

    def __init__(self, max_pending: int = 64, faults=None):
        self._max_pending = max_pending
        self._faults = faults
        self.last_bytes = 0
        self._pending: collections.OrderedDict = collections.OrderedDict()
        # mids whose partial was evicted: their LATE chunks must be
        # dropped, not allowed to recreate a can-never-complete partial
        # that would occupy a slot and evict further live messages
        self._evicted: collections.OrderedDict = collections.OrderedDict()

    def _count_oversize(self) -> None:
        if self._faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            self._faults = default_fault_counters
        self._faults.inc("oversize_frames")

    def feed(self, raw: bytes):
        if raw[:4] != CHUNK_MAGIC:
            if len(raw) > MAX_ASSEMBLED_BYTES:
                self._count_oversize()
                raise CorruptFrame(
                    f"frame of {len(raw)} bytes exceeds the "
                    f"{MAX_ASSEMBLED_BYTES}-byte assembled cap")
            self.last_bytes = len(raw)
            return decode(raw)
        if len(raw) < _HDR_LEN + _CHUNK_HDR:
            raise CorruptFrame(f"chunk frame truncated ({len(raw)} bytes)")
        (want,) = struct.unpack_from(">I", raw, 4)
        body = memoryview(raw)[8:]
        if zlib.crc32(body) != want:
            raise CorruptFrame("chunk frame checksum mismatch")
        mid = bytes(body[:16])
        idx, total = struct.unpack_from(">II", body, 16)
        if not 0 < total <= _MAX_CHUNKS or idx >= total:
            raise CorruptFrame(f"chunk index {idx}/{total} out of range")
        (ctx_len,) = struct.unpack_from(">H", body, 24)
        if ctx_len > _MAX_CTX_BYTES or _CHUNK_HDR + ctx_len > len(body):
            raise CorruptFrame(f"chunk frame claims {ctx_len}-byte "
                               "trace context")
        ctx = bytes(body[_CHUNK_HDR:_CHUNK_HDR + ctx_len])
        if mid in self._evicted:
            return None
        ent = self._pending.get(mid)
        if ent is None:
            ent = self._pending[mid] = {"total": total, "parts": {},
                                        "ctx": ctx, "bytes": 0}
            while len(self._pending) > self._max_pending:
                dead, _ = self._pending.popitem(last=False)
                self._evicted[dead] = True
                while len(self._evicted) > 4 * self._max_pending:
                    self._evicted.popitem(last=False)
        if ent["total"] != total:
            raise CorruptFrame("chunk total mismatch within message")
        if idx not in ent["parts"]:
            ent["parts"][idx] = bytes(body[_CHUNK_HDR + ctx_len:])
            ent["bytes"] += len(raw)
            # the broker's frame cap is per FRAME; a chunked message's
            # ASSEMBLED size must honor the same bound or a legal chunk
            # stream smuggles an arbitrarily large allocation past it
            if ent["bytes"] > MAX_ASSEMBLED_BYTES:
                del self._pending[mid]
                self._evicted[mid] = True
                self._count_oversize()
                raise CorruptFrame(
                    f"chunked message exceeds the "
                    f"{MAX_ASSEMBLED_BYTES}-byte assembled cap "
                    f"({ent['bytes']} bytes across "
                    f"{len(ent['parts'])}/{total} chunks)")
        if len(ent["parts"]) < total:
            return None
        del self._pending[mid]
        self.last_bytes = ent["bytes"]
        msg = decode(b"".join(ent["parts"][i] for i in range(total)))
        if ent["ctx"] and getattr(msg, "_ctx", None) is None:
            # chunked legacy frame: the chunk headers carried the only
            # copy of the context (TENSOR frames restore their own)
            msg._ctx = ent["ctx"]
        return msg
