"""Typed control-plane protocol.

The reference's wire vocabulary is untyped dicts with an ``action`` key
pushed through RabbitMQ (client→server REGISTER ``client.py:57``, NOTIFY
``src/train/VGG16.py:121-126``, UPDATE ``src/RpcClient.py:128-132``;
server→client START ``src/Server.py:262-272``, SYN ``:293-296``, PAUSE
``:140-153``, STOP ``:276-287``).  Here every message is a dataclass; a
READY ack is added so the server's 25-second settle sleep
(``src/Server.py:289`` — a time-based barrier papering over a race,
SURVEY.md §5.2) becomes an explicit barrier.

Queue naming keeps the reference topology so the protocol surface maps
1:1 (SURVEY.md §1 L0 table):

* ``rpc_queue``                              any client → server
* ``reply_{client_id}``                      server → one client
* ``intermediate_queue_{stage}_{cluster}``   stage k → k+1 activations
  (shared per cluster — natural load balance across same-stage clients)
* ``gradient_queue_{stage}_{client_id}``     stage k+1 → one stage-k client
"""

from __future__ import annotations

import dataclasses
import io
import pickle
import struct
import zlib
from typing import Any

import numpy as np

RPC_QUEUE = "rpc_queue"


def reply_queue(client_id: str) -> str:
    return f"reply_{client_id}"


def intermediate_queue(stage: int, cluster: int,
                       pair: int | None = None) -> str:
    """Forward-activation queue.  ``pair`` selects 2LS's fixed 1:1
    edge<->head pairing (``intermediate_queue_{layer}_{idx}``,
    ``other/2LS/src/train/VGG16.py:23``) instead of the shared
    per-cluster queue's natural load balancing."""
    base = f"intermediate_queue_{stage}_{cluster}"
    return base if pair is None else f"{base}_p{pair}"


def gradient_queue(stage: int, client_id: str) -> str:
    return f"gradient_queue_{stage}_{client_id}"


# --------------------------------------------------------------------------
# control messages
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Register:
    """client → server: join the round (with the offline profile)."""
    client_id: str
    stage: int                      # 1-based stage index ("layer_id")
    cluster: int | None = None      # manual cluster assignment, or None
    profile: dict | None = None     # {exe_time, size_data, speed, network}


@dataclasses.dataclass
class Ready:
    """client → server: shard built, data loaded — replaces sleep(25).

    ``round_idx`` carries the START's generation: a late READY from an
    invocation the server already gave up on must not count toward a
    newer invocation's READY barrier (the server would then SYN a client
    that is still unwinding the old round)."""
    client_id: str
    round_idx: int = 0


@dataclasses.dataclass
class Notify:
    """stage-1 client → server: local data exhausted this round.

    ``round_idx`` fences the barrier: a straggler's NOTIFY from a round
    the server already dropped must not satisfy a later round's barrier."""
    client_id: str
    cluster: int
    round_idx: int = 0


@dataclasses.dataclass
class Update:
    """client → server: round's trained shard parameters.

    ``round_idx`` fences aggregation: without it, a straggler dropped in
    round N that wakes during round N+1 would have its stale round-N
    weights counted as N+1's contribution."""
    client_id: str
    stage: int
    cluster: int
    params: Any                     # pytree of np arrays (host-side)
    num_samples: int                # FedAvg weight (data_count semantics)
    ok: bool = True                 # False -> NaN seen, skip aggregation
    batch_stats: Any | None = None  # shard's running stats (BN models)
    round_idx: int = 0


@dataclasses.dataclass
class Start:
    """server → client: round config + shard weights."""
    start_layer: int
    end_layer: int                  # -1 = to the end
    cluster: int
    params: Any                     # shard pytree (np arrays)
    batch_stats: Any | None = None
    learning: dict | None = None    # lr/momentum/... overrides
    label_counts: Any | None = None  # stage-1: per-label sample counts
    round_idx: int = 0
    extra: dict | None = None       # strategy-specific knobs (sda_size, ...)


@dataclasses.dataclass
class Syn:
    """server → client: begin training.

    ``sda_fence_quorum`` / ``sda_feeders``, when set, override the
    static values sent in START: the server recomputes them from the
    RESPONSIVE client set after the READY barrier, so a previous-stage
    client dropped mid-round (whose fence copies will never arrive)
    can't leave the strict-SDA drain waiting on a quorum that can no
    longer be met (ADVICE round 5)."""
    round_idx: int = 0
    sda_fence_quorum: int | None = None
    sda_feeders: list | None = None


@dataclasses.dataclass
class Pause:
    """server → client: stop the hot loop, upload weights.

    ``send_weights=False`` is FLEX's non-aggregation-round PAUSE
    (``other/FLEX/src/Server.py:140-143``)."""
    send_weights: bool = True


@dataclasses.dataclass
class Stop:
    """server → client: terminate."""
    reason: str = ""


# --------------------------------------------------------------------------
# data-plane messages
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Activation:
    """stage k → stage k+1. ``trace`` is the routing stack of client_ids,
    appended per forward hop, popped per backward hop
    (``src/train/VGG16.py:24-31``, ``:41-43``).  ``round_idx`` fences
    rounds: a consumer drops messages stamped with a different round, so
    activations published into a round the server already dropped (elastic
    mid-round PAUSE) can't leak into the next round's batches — the
    reference has no such fence because its queues only ever carry one
    round at a time (it hangs instead of dropping rounds, SURVEY.md §5.3)."""
    data_id: str
    data: Any          # ndarray, or a pytree of ndarrays for models whose
    labels: np.ndarray  # stage boundaries carry extras (e.g. BERT's mask)
    trace: list
    cluster: int
    round_idx: int = 0


@dataclasses.dataclass
class Gradient:
    """stage k+1 → the originating stage-k client."""
    data_id: str
    data: Any   # cotangent, same pytree structure as the Activation.data
    trace: list
    round_idx: int = 0


@dataclasses.dataclass
class EpochEnd:
    """stage k → stage k+1 (strict-SDA only): the feeder has dispatched
    its last batch of this epoch.  DCSL's hard ``sda_size`` window
    drains its leftovers only at epoch end
    (``other/DCSL/src/Scheduler.py:152-191`` processes full windows,
    then the epoch boundary clears the queues); this marker is how the
    head learns the boundary without the server round-trip.  Rides the
    data-plane queues so per-queue FIFO ordering guarantees it arrives
    AFTER every activation it fences.

    In >2-stage plans middle stages PROPAGATE the marker to every
    downstream queue, but only once the full previous-stage quorum of
    copies has arrived (``sda_fence_quorum``): a receiver hears one
    copy per previous-stage device, and only the LAST copy proves —
    via per-queue FIFO — that every activation the fence covers has
    arrived, whichever previous-stage device relayed it."""
    client_id: str
    round_idx: int = 0
    epoch: int = 0


@dataclasses.dataclass
class QuantLeaf:
    """One int8 absmax-quantized float tensor on the data-plane wire
    (``transport.wire-dtype: int8`` — ~4x smaller than the reference's
    fp32 pickles, ``src/train/VGG16.py:27``): ``x ≈ q * scale`` with
    ``scale = max|x| / 127``.  Deliberately NOT a registered pytree so
    tree_maps over a wire payload treat it as a leaf."""
    q: np.ndarray       # int8
    scale: float        # dequantization factor


CONTROL_TYPES = (Register, Ready, Notify, Update, Start, Syn, Pause, Stop)
DATA_TYPES = (Activation, Gradient, EpochEnd)
_TYPE_BY_NAME = {t.__name__: t for t in CONTROL_TYPES + DATA_TYPES}
#: nested wire-format helpers (never valid as a top-level message)
_WIRE_HELPERS = {"QuantLeaf": QuantLeaf}


# --------------------------------------------------------------------------
# serialization
# --------------------------------------------------------------------------
# Arrays are framed out-of-band (np.save) and the remainder pickled; a
# restricted unpickler only admits protocol dataclasses + builtins, unlike
# the reference's bare pickle.loads of broker bytes (SURVEY.md §1 L0).
#
# Every frame is checksummed: ``MAGIC | crc32(body) | body``.  A corrupt
# or truncated frame raises :class:`CorruptFrame` BEFORE any unpickling —
# bit-rot on the wire (or an injected chaos fault) must never reach the
# unpickler, whose failure modes on garbage are arbitrary exceptions deep
# inside numpy reconstruction.

FRAME_MAGIC = b"SLT1"
_HDR_LEN = len(FRAME_MAGIC) + 4


class CorruptFrame(pickle.UnpicklingError):
    """Frame failed the integrity check (bad magic / length / checksum).

    Subclasses UnpicklingError so callers guarding decode() with the
    pre-checksum except clause keep working."""


class _SafeUnpickler(pickle.Unpickler):
    _ALLOWED = {
        ("builtins", "dict"), ("builtins", "list"), ("builtins", "tuple"),
        ("builtins", "set"), ("builtins", "frozenset"),
        ("builtins", "complex"), ("builtins", "bytearray"),
        ("numpy", "dtype"), ("numpy", "ndarray"),
        ("ml_dtypes", "bfloat16"),  # compressed wire payloads
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.numeric", "_frombuffer"),
        ("numpy.core.numeric", "_frombuffer"),
    }

    def find_class(self, module, name):
        if module == "split_learning_tpu.runtime.protocol":
            if name in _TYPE_BY_NAME:
                return _TYPE_BY_NAME[name]
            if name in _WIRE_HELPERS:
                return _WIRE_HELPERS[name]
        if (module, name) in self._ALLOWED:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"disallowed class in protocol message: {module}.{name}")


def encode(msg) -> bytes:
    if type(msg).__name__ not in _TYPE_BY_NAME:
        raise TypeError(f"not a protocol message: {type(msg)!r}")
    body = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    return FRAME_MAGIC + struct.pack(">I", zlib.crc32(body)) + body


def decode(raw: bytes):
    if len(raw) < _HDR_LEN or raw[:len(FRAME_MAGIC)] != FRAME_MAGIC:
        raise CorruptFrame(
            f"protocol frame missing magic/header ({len(raw)} bytes)")
    (want,) = struct.unpack_from(">I", raw, len(FRAME_MAGIC))
    body = raw[_HDR_LEN:]
    if zlib.crc32(body) != want:
        raise CorruptFrame("protocol frame checksum mismatch "
                           f"({len(raw)} bytes)")
    msg = _SafeUnpickler(io.BytesIO(body)).load()
    # wire helpers (QuantLeaf) are only valid NESTED in a payload — a
    # bare one must fail here, not as an AttributeError in a hot loop
    if not isinstance(msg, CONTROL_TYPES + DATA_TYPES):
        raise pickle.UnpicklingError(
            f"not a protocol message: {type(msg).__name__}")
    return msg
