"""Round planning: registrations → clusters, cut points, data assignment.

This is the server-side planning pass the reference runs once all clients
have registered (``/root/reference/src/Server.py:111-135`` registration
barrier → ``:87-101`` label-distribution synthesis → ``:300-382``
``cluster_and_selection``): KMeans clustering of stage-1 clients by label
distribution, GMM straggler rejection, and per-cluster cut-point search —
all reimplemented as pure functions in :mod:`split_learning_tpu.planner`.
The output :class:`ClusterPlan` list is what both execution backends (the
in-process mesh context and the multi-process protocol server) consume.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from split_learning_tpu.config import Config
from split_learning_tpu.models import num_layers
from split_learning_tpu.planner import (
    auto_threshold, clustering_algorithm, partition,
    synthesize_label_counts,
)

#: classes per dataset (reference: implicit in each loader/model pairing)
DATASET_CLASSES = {
    "CIFAR10": 10, "CIFAR100": 100, "MNIST": 10,
    "AGNEWS": 4, "EMOTION": 6, "SPEECHCOMMANDS": 10,
}


@dataclasses.dataclass
class Registration:
    """One client's REGISTER payload (``client.py:57-59``)."""
    client_id: str
    stage: int                       # 1-based
    cluster: int | None = None       # manual assignment
    profile: dict | None = None      # {exe_time, size_data, speed, network}


@dataclasses.dataclass
class ClusterPlan:
    """Everything one cluster needs for a round."""
    cluster_id: int
    cuts: list                       # 1-based cut layers, len = n_stages-1
    clients: list                    # per-stage lists of client_ids
    label_counts: np.ndarray         # (n_stage1_clients, n_classes)
    rejected: list                   # client_ids dropped by selection

    @property
    def n_stages(self) -> int:
        return len(self.clients)

    @property
    def stage1_clients(self) -> list:
        return self.clients[0]

    def stage_of(self, client_id: str) -> int:
        for s, ids in enumerate(self.clients, start=1):
            if client_id in ids:
                return s
        raise KeyError(client_id)

    def all_clients(self) -> list:
        return [c for ids in self.clients for c in ids]


def pipeline_slots(cfg: Config) -> list[dict]:
    """Deterministic later-stage client slots for the cross-host MPMD
    stage pipeline (``pipeline.remote``): every stage >= 2 client the
    configured counts call for, as plain dicts a
    :class:`~split_learning_tpu.runtime.protocol.StageAssign` can
    carry.  Ids follow the deployment convention
    ``client_{stage}_{index}`` so a single-process twin running the
    same ids produces a BIT-IDENTICAL fold (the per-client ShardRunner
    seed is a client-id hash), and so a slot re-assigned to a
    surviving host after a death keeps its identity.  Stage-0 feeders
    are not slots — they own the data and stay wherever the
    deployment put them."""
    slots: list[dict] = []
    for s in range(2, cfg.num_stages + 1):
        for i in range(cfg.clients[s - 1]):
            slots.append({"client_id": f"client_{s}_{i}",
                          "stage": s, "cluster": None})
    return slots


def _num_classes(cfg: Config) -> int:
    return DATASET_CLASSES.get(cfg.dataset, 10)


def prune_plan_members(plans: list, pruned: set) -> list | None:
    """Remove ``pruned`` clients from plans without re-planning; None
    when any cluster would lose a whole stage (an empty pipeline stage
    cannot run).  Shared by the server's elastic prune and the
    scheduler's eviction — one copy of the feasibility invariant."""
    if not pruned:
        return None
    new_plans = []
    for p in plans:
        keep = [i for i, c in enumerate(p.stage1_clients)
                if c not in pruned]
        clients = [[c for c in ids if c not in pruned]
                   for ids in p.clients]
        if any(not ids for ids in clients):
            return None
        new_plans.append(dataclasses.replace(
            p, clients=clients,
            label_counts=np.asarray(p.label_counts)[keep]))
    return new_plans


def plan_clusters(cfg: Config,
                  registrations: list[Registration],
                  exact_counts: bool = True) -> list[ClusterPlan]:
    """Full planning pass. Registrations must cover ``cfg.clients`` counts
    (stage s gets cfg.clients[s-1] clients); with ``exact_counts=False``
    (elastic re-planning between rounds) any membership works as long as
    every stage keeps at least one client — a pipeline with an empty
    stage cannot run."""
    n_stages = cfg.num_stages
    by_stage: dict[int, list[Registration]] = {s: [] for s in
                                               range(1, n_stages + 1)}
    for reg in registrations:
        if reg.stage not in by_stage:
            raise ValueError(
                f"client {reg.client_id} registered for stage {reg.stage}, "
                f"config has {n_stages} stages")
        by_stage[reg.stage].append(reg)
    for s in range(1, n_stages + 1):
        if exact_counts and len(by_stage[s]) != cfg.clients[s - 1]:
            raise ValueError(
                f"stage {s}: expected {cfg.clients[s - 1]} clients, "
                f"got {len(by_stage[s])}")
        if not by_stage[s]:
            raise ValueError(f"stage {s}: no clients registered")

    stage1 = by_stage[1]
    if cfg.topology.mode == "auto" and cfg.topology.require_profiles:
        # fail-fast contract (reference client.py:52-62: clients refuse
        # to start without profiling.json): auto partitioning must not
        # silently degrade to an even split
        missing = [r.client_id for r in stage1
                   if not (r.profile and "exe_time" in r.profile
                           and "size_data" in r.profile)]
        if missing:
            raise ValueError(
                "topology.require_profiles: auto partitioning needs a "
                "profile (exe_time + size_data) from every stage-1 "
                f"client; missing from {missing} — run "
                "`python -m split_learning_tpu.profiler` on each client "
                "or disable require-profiles")
    n_classes = _num_classes(cfg)
    dist = cfg.distribution
    label_counts = synthesize_label_counts(
        len(stage1), n_classes, dist.num_samples,
        non_iid=(dist.mode == "dirichlet"), alpha=dist.alpha,
        seed=dist.seed if dist.seed is not None else cfg.seed)
    if dist.mode == "fixed":
        label_counts = np.asarray(dist.matrix, dtype=int)
        if label_counts.shape[0] != len(stage1):
            raise ValueError(
                f"fixed distribution matrix has {label_counts.shape[0]} "
                f"rows, need {len(stage1)}")

    k = cfg.topology.num_clusters
    # -- cluster assignment of stage-1 clients --------------------------
    if cfg.topology.mode == "auto" and k > 1:
        labels, _ = clustering_algorithm(label_counts, k)
    else:
        # manual: honor Register.cluster when provided (and in range),
        # else round-robin
        labels = np.array([
            reg.cluster if reg.cluster is not None
            and 0 <= reg.cluster < k else i % k
            for i, reg in enumerate(stage1)
        ])

    # -- straggler rejection (GMM on speed) -----------------------------
    rejected_ids: set = set()
    if cfg.topology.selection:
        speeds = np.array([
            (reg.profile or {}).get("speed", 1.0) for reg in stage1
        ], dtype=float)
        if len(set(speeds.tolist())) > 1:
            thr = auto_threshold(speeds)
            for reg, sp in zip(stage1, speeds):
                if sp < thr:
                    rejected_ids.add(reg.client_id)

    # -- later-stage clients: manual cluster or round-robin -------------
    later_assign: dict[int, list[list]] = {}
    for s in range(2, n_stages + 1):
        buckets: list[list] = [[] for _ in range(k)]
        unassigned = []
        for reg in by_stage[s]:
            if reg.cluster is not None and 0 <= reg.cluster < k:
                buckets[reg.cluster].append(reg.client_id)
            else:
                unassigned.append(reg.client_id)
        for i, cid in enumerate(unassigned):
            order = sorted(range(k), key=lambda c: len(buckets[c]))
            buckets[order[0]].append(cid)
        later_assign[s] = buckets

    # -- per-cluster cut points -----------------------------------------
    n_layer = num_layers(cfg.model_key, **(cfg.model_kwargs or {}))
    plans: list[ClusterPlan] = []
    for c in range(k):
        members = [i for i in range(len(stage1)) if labels[i] == c]
        kept = [i for i in members
                if stage1[i].client_id not in rejected_ids]
        if not kept:
            kept = members  # never reject a whole cluster
        cuts = _cluster_cuts(cfg, c, [stage1[i] for i in kept],
                             later_assign, n_layer)
        clients = [[stage1[i].client_id for i in kept]]
        for s in range(2, n_stages + 1):
            clients.append(list(later_assign[s][c]))
        plans.append(ClusterPlan(
            cluster_id=c, cuts=cuts, clients=clients,
            label_counts=label_counts[kept],
            rejected=[stage1[i].client_id for i in members
                      if stage1[i].client_id in rejected_ids]))
    return [p for p in plans if p.stage1_clients]


def _cluster_cuts(cfg: Config, cluster_id: int, stage1_regs: list,
                  later_assign: dict, n_layer: int) -> list:
    topo = cfg.topology
    n_cuts = cfg.num_stages - 1
    if n_cuts == 0:
        return []
    if topo.mode == "manual":
        if topo.cluster_cut_layers is not None:
            return list(topo.cluster_cut_layers[cluster_id])
        return list(topo.cut_layers)[:n_cuts]
    # auto: throughput-balance search over profiles (src/Partition.py:2-21)
    profs = [r.profile for r in stage1_regs if r.profile]
    if not profs or "exe_time" not in profs[0] \
            or "size_data" not in profs[0]:
        # no profiles -> even layer split
        return [max(1, (i + 1) * n_layer // (n_cuts + 1))
                for i in range(n_cuts)]
    exe1 = [p["exe_time"] for p in profs]
    # `or`: an unprobed profile carries network=0.0 — treat as unconstrained
    net1 = [float(p.get("network") or 1e9) for p in profs]
    # profiles record fp32 boundary bytes; a compressed data-plane wire
    # (transport.wire-dtype) shrinks what actually crosses per hop, and
    # the throughput-balance search must weigh the cut with the bytes it
    # will really ship (non-float extras like masks are negligible)
    wire_factor = {"float32": 1.0, "float16": 0.5,
                   "bfloat16": 0.5, "int8": 0.25}[
                       cfg.transport.wire_dtype_normalized]
    size_data = [s * wire_factor for s in profs[0]["size_data"]]
    # later-stage devices are unprofiled at the server (the reference also
    # only keeps stage-1 size_data — src/Server.py:115-117); mirror group 1
    if n_cuts == 1:
        return partition(exe1, net1, exe1, net1, size_data)
    from split_learning_tpu.planner import partition_multiway
    return partition_multiway([exe1] * (n_cuts + 1),
                              [net1] * (n_cuts + 1), size_data)
