"""Streaming sharded aggregation plane (ROADMAP item 4).

The server used to materialize one full parameter tree per client and
FedAvg-fold them all at once at the UPDATE barrier
(``runtime/server.py:_fold_update`` collecting, then
``runtime/strategies.py:aggregate_cluster`` folding) — aggregate wall
and host memory grew linearly with fleet width while every client idled
behind the slowest one.  This module rebuilds that data plane as a
streaming, hierarchical, optionally mesh-sharded fold:

* :class:`StreamingFold` — an incremental weighted-sum accumulator.
  Each Update folds into a per-stage running sum the moment the server
  decodes it, so the barrier holds O(1) parameter trees instead of
  O(clients) and per-client fold cost is constant.  **Determinism
  contract**: contributions fold in the canonical ``(stage,
  client_id)`` order whatever order frames arrive — a small reorder
  window holds early arrivals until their predecessors land (or are
  dropped), so the float summation sequence is exactly the barrier
  oracle's (``aggregate_cluster`` over the client-id-sorted list) and
  the result is **bit-identical** to it, chaos dup/reorder/drop
  included.  Window memory is O(arrival skew): zero when updates land
  in client order, and never worse than the old barrier's O(clients).

* :class:`L1Aggregator` — the aggregator tree (``aggregation.fan-in``):
  clients publish their Update to a per-group ``aggregate_queue_*``
  instead of ``rpc_queue``; an L1 aggregator folds its ≤ fan-in members
  into one :class:`~split_learning_tpu.runtime.protocol.PartialAggregate`
  (per-stage weighted SUMS + total weight, so the root continues the
  fold without re-dividing) published to the server.  Per-node fan-in
  stays constant at 100+ clients.  An L1 that dies mid-round degrades
  to direct-to-root: the server drains the orphaned group queue itself
  (counted ``agg_l1_fallbacks``) and folds the members at the group's
  canonical position, so tree rounds stay deterministic.  Note the
  tree changes the summation SHAPE (``(a+b)+(c+d)`` vs the flat
  ``((a+b)+c)+d``), so tree mode is deterministic-but-not-bit-identical
  to the flat fold — the documented trade for constant fan-in.

* :class:`MeshFoldBackend` — the running sum, the FedAvg divide and the
  server-side optimizer step run as jitted elementwise ops on arrays
  sharded across the server's device mesh (leaf axis 0 over an ``agg``
  axis, the shard/gather-fn pattern), instead of replicated host
  pytrees; accumulator buffers are donated so the fold updates in
  place.  :class:`HostFoldBackend` is the numpy twin — both replicate
  ``ops/fedavg.py:_avg_leaves`` op for op, so host and mesh folds are
  bit-identical on CPU.

* server-side optimizer (``aggregation.server-momentum``, FedAvgM):
  ``v = m·v + (base - avg); new = base - v`` applied leafwise inside
  the fold's finalize — with ``m = 0`` (default) this is plain FedAvg.
  Velocity lives in the backend's (sharded) representation between
  rounds.

* **sharded weight-update plane** (``aggregation.update-sharded``,
  "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
  Training", arxiv 2004.13336): the whole round-boundary update —
  FedAvg divide, FedAvgM step, wire-dtype cast for START — runs as ONE
  fused program per stage (:meth:`MeshFoldBackend.stage_update`:
  jitted, accumulator/velocity buffers donated, every leaf sharded
  along axis 0 over the ``agg`` axis via the shared
  :func:`~split_learning_tpu.parallel.axes.leaf_axis0_spec` rule),
  with a single device->host fetch per stage.  ``finish(on_stage=...)``
  dispatches every stage's program before fetching any, then streams
  each stage's host trees to the callback in stage order — stage k's
  fetch + START encode overlap stage k+1's device compute, the
  per-shard pipelining that (with the clients' ``learning.sync-overlap``
  ticks) hides the round-boundary update wall.

* **multi-level, multi-process tree** (``aggregation.levels`` /
  ``aggregation.remote``): :func:`plan_tree` generalizes the fan-in
  grouping recursively — interior groups fold their children's
  PartialAggregates (sums of sums with total weight, so any depth
  divides exactly once at the root), and every group's input is
  simply ``aggregate_queue(cluster, idx)`` (indices globally unique
  across levels).  :class:`L1Aggregator` serves any level; with
  ``aggregation.remote`` the same fold logic runs inside standalone
  aggregator processes (``runtime/aggnode.py``,
  ``tools/sl_aggregator.py``) adopted over the broker, with liveness
  via the HEARTBEAT/FleetMonitor plane and the counted direct-to-root
  fallback drain on node death.  The partial-sum wire optionally
  compresses through the ``partial`` codec family
  (``runtime/codec/partial.py``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any

import numpy as np

from split_learning_tpu.ops.fedavg import (
    is_int_dtype as _is_int_dtype, unflatten_items as _unflatten,
    walk_items as _flat_items,
)
from split_learning_tpu.runtime.protocol import (
    FrameAssembler, PartialAggregate, Update, aggregate_queue,
    encode_parts, RPC_QUEUE,
)

#: strategies whose per-invocation aggregation consumes the WHOLE update
#: list at once (``aggregate_cluster(ups)``) — the only shape a
#: streaming fold can replace.  relay / periodic / fedasync read
#: individual ``u.params`` (per-client persistence, subset merges), so
#: they keep the barrier semantics and streaming stays off.
FOLD_STRATEGIES = frozenset({"fedavg", "sda", "cluster_relay"})


class UpdateBatch(list):
    """``train_cluster``'s return value when a streaming fold ran: the
    (weight-stripped) Update list plus the precomputed fold result that
    ``aggregate_cluster`` consumes instead of re-folding."""
    fold: "FoldResult | None" = None


@dataclasses.dataclass
class FoldResult:
    params: Any
    stats: Any
    n_samples: int
    fold_s: float = 0.0            # wall spent folding (overlapped)
    peak_tree_copies: float = 0.0  # window HWM in full-tree equivalents
    window_hwm: int = 0            # most simultaneous held contributions
    folded: int = 0                # contributions folded
    partials: int = 0              # PartialAggregate contributions
    update_s: float = 0.0          # round-boundary update wall (divide +
    # momentum + cast + device->host fetch), the serial bubble the
    # sharded update + sync overlap exist to shrink/hide
    stage_update_ms: dict = dataclasses.field(default_factory=dict)
    # per-stage update wall (ms), keyed by stage — the per-shard
    # streaming granularity


# --------------------------------------------------------------------------
# fold backends
# --------------------------------------------------------------------------
# Both replicate ops/fedavg.py:_avg_leaves op for op:
#   t   = nan_to_num(leaf.astype(f32)) * w
#   acc = t | acc + t          (canonical order)
#   avg = acc / total_w        (int leaves: round first)
# so a streamed fold is bit-identical to the barrier fold, and the mesh
# backend is bit-identical to the host one on CPU (elementwise IEEE ops).

def _mom_path_set(st: "_StageFold", base_flat, momentum: float) -> set:
    """Paths the FedAvgM step applies to: float leaves present in the
    base tree (int leaves and paths outside the base keep plain
    FedAvg) — the single definition both backends' fused stage update
    and the legacy per-leaf path share."""
    if not momentum or base_flat is None:
        return set()
    return {p for p in st.acc
            if p in base_flat and not _is_int_dtype(st.dtype[p])}


def _stage_velocity(st: "_StageFold", base_flat, velocity,
                    mom_paths: set) -> dict:
    """This stage's usable velocity entries (an elastic re-plan can
    leave a path's velocity shaped for another tensor — restart those
    from zero, exactly like the legacy per-leaf path did)."""
    out = {}
    for p in mom_paths:
        vel = (velocity or {}).get(p)
        if vel is not None and np.shape(vel) != np.shape(base_flat[p]):
            vel = None
        out[p] = vel
    return out


class HostFoldBackend:
    """Numpy accumulate/divide — the single-host default."""

    name = "host"

    def contrib(self, leaf, w) -> np.ndarray:
        return np.nan_to_num(np.asarray(leaf, dtype=np.float32)) * w

    def ingest(self, sums_leaf) -> np.ndarray:
        """Adopt a PartialAggregate's precomputed f32 sum leaf.

        ``nan_to_num`` like :meth:`contrib`: a partial's sums arrive
        over the wire (f32 overflow at an L1, a corrupt-but-crc-lucky
        frame) and are the one fold input the contribution path's
        sanitizer never saw — a no-op on every finite value, so clean
        runs keep their bit-identity contracts."""
        return np.nan_to_num(np.asarray(sums_leaf, dtype=np.float32))

    def add(self, acc, t):
        return acc + t

    def finalize(self, acc, total_w: float, dtype) -> np.ndarray:
        avg = acc / np.float32(total_w)
        if _is_int_dtype(dtype):
            return np.round(avg).astype(dtype)
        return avg.astype(dtype)

    def momentum_step(self, base, avg32, vel, m: float):
        """FedAvgM: returns (new_param_f32, new_velocity)."""
        b = np.asarray(base, dtype=np.float32)
        v = m * vel + (b - avg32) if vel is not None else (b - avg32)
        return b - v, v

    def stage_update(self, st: "_StageFold", base_flat, velocity,
                     momentum: float):
        """Fused per-stage round-boundary update, host twin: FedAvg
        divide + FedAvgM step + cast back to the START wire dtype for
        EVERY leaf of one stage, as one call.  Returns an opaque
        pending handle for :meth:`stage_fetch` (eager here; the mesh
        backend dispatches async so stage k+1's compute overlaps
        stage k's fetch/encode)."""
        mom_paths = _mom_path_set(st, base_flat, momentum)
        vels = _stage_velocity(st, base_flat, velocity, mom_paths)
        params: dict = {}
        new_vel: dict = {}
        for path, acc in st.acc.items():
            dt = st.dtype[path]
            if path in mom_paths:
                avg32 = self.finalize(acc, st.total_w,
                                      np.dtype(np.float32))
                new32, nv = self.momentum_step(base_flat[path], avg32,
                                               vels[path], momentum)
                new_vel[path] = nv
                params[path] = np.asarray(new32).astype(dt)
            else:
                params[path] = self.finalize(acc, st.total_w, dt)
        stats = {p: self.finalize(a, st.stat_total_w, st.stat_dtype[p])
                 for p, a in st.stat_acc.items()}
        return params, stats, new_vel

    def stage_fetch(self, pending):
        return pending

    def to_host(self, x) -> np.ndarray:
        return np.asarray(x)

    def nbytes(self, x) -> int:
        return np.asarray(x).nbytes


class MeshFoldBackend:
    """Accumulate/divide/optimizer as jitted ops on arrays sharded over
    the server's device mesh (``aggregation.sharded``).

    Each leaf shards along axis 0 over a 1-D ``agg`` mesh axis when the
    axis divides evenly (replicated otherwise — small leaves are not
    worth a ragged layout).  The add donates the accumulator buffer, so
    per-client fold cost is one sharded elementwise add with no fresh
    allocation; only ``finalize`` gathers to host.
    """

    name = "mesh"

    def __init__(self, devices=None, kernels=None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        from split_learning_tpu.ops import kernels as kplane
        # Pallas kernel plan for the fused stage update (kernels:
        # config block; None = the process-wide plan), captured at
        # construction so one backend's programs are self-consistent
        self._kplan = kplane.as_plan(kernels)
        self._jax = jax
        devs = list(devices) if devices is not None else jax.devices()
        self.n_devices = len(devs)
        self.mesh = Mesh(np.asarray(devs), ("agg",))
        self._NS, self._P = NamedSharding, PartitionSpec
        self._contrib = jax.jit(
            lambda x, w: jnp.nan_to_num(x.astype(jnp.float32)) * w)
        # `acc` naming is load-bearing: the JX007 audit
        # (analysis/jaxpr_audit.py) statically requires every jitted
        # op consuming a running-accumulator parameter to donate it
        self._add = jax.jit(lambda acc, t: acc + t, donate_argnums=(0,))
        self._div = jax.jit(lambda a, tw: a / tw)
        self._div_round = jax.jit(lambda a, tw: jnp.round(a / tw))
        # FedAvgM inner step: v' = m v + (b - a); p' = b - v'
        def _mom(b, a, v, m):
            nv = m * v + (b - a)
            return b - nv, nv
        self._mom = jax.jit(_mom)
        # fused per-stage round-boundary update programs, keyed by the
        # stage's static structure signature (paths/shapes/dtypes +
        # which paths take the momentum step) — see _fused_update.
        # Bounded like client._OPS_CACHE: elastic re-plans mint fresh
        # signatures, and each entry pins a compiled XLA executable.
        self._fused_cache: dict = {}
        self._fused_cache_max = 32

    def _sharding(self, shape):
        from split_learning_tpu.parallel.axes import leaf_axis0_spec
        spec = leaf_axis0_spec(tuple(shape), self.n_devices, "agg")
        return self._NS(self.mesh, spec)

    # -- fused sharded stage update (aggregation.update-sharded) ---------

    def _fused_update(self, sig, dtypes, stat_dtypes, mom_paths):
        """One jitted program for one stage's ENTIRE round-boundary
        update: FedAvg divide, FedAvgM momentum step, and the cast
        back to each leaf's START wire dtype — every leaf sharded
        along axis 0 over the ``agg`` mesh axis (the ZeRO-style
        leaf-axis-0 rule), accumulator and velocity buffers DONATED so
        the update happens in place.  The elementwise op sequence
        matches the host twin exactly, so mesh and host stay
        bit-identical on CPU."""
        prog = self._fused_cache.get(sig)
        if prog is not None:
            return prog
        jax = self._jax
        import jax.numpy as jnp
        kplan = self._kplan
        if kplan.stage_update:
            from split_learning_tpu.ops.kernels import update as kupd

        def fused(acc, stat_acc, base, vel, tw, stat_tw, m):
            params, stats, nvel = {}, {}, {}
            for path in sorted(acc):
                dt = dtypes[path]
                if kplan.stage_update and kupd.kernel_ok(acc[path]):
                    # single-pass Pallas finish (same op order as the
                    # jnp chain below — mesh/host stay bit-identical)
                    if path in mom_paths:
                        p, nv = kupd.momentum_leaf(
                            acc[path], base[path], vel[path], tw, m,
                            dt, block=kplan.block)
                        nvel[path] = nv
                        params[path] = p
                    else:
                        params[path] = kupd.finalize_leaf(
                            acc[path], tw, dt, rnd=_is_int_dtype(dt),
                            block=kplan.block)
                    continue
                a32 = acc[path] / tw
                if path in mom_paths:
                    nv = m * vel[path] + (base[path] - a32)
                    nvel[path] = nv
                    params[path] = (base[path] - nv).astype(dt)
                elif _is_int_dtype(dt):
                    params[path] = jnp.round(a32).astype(dt)
                else:
                    params[path] = a32.astype(dt)
            for path in sorted(stat_acc):
                dt = stat_dtypes[path]
                if kplan.stage_update and kupd.kernel_ok(
                        stat_acc[path]):
                    stats[path] = kupd.finalize_leaf(
                        stat_acc[path], stat_tw, dt,
                        rnd=_is_int_dtype(dt), block=kplan.block)
                    continue
                s32 = stat_acc[path] / stat_tw
                stats[path] = (jnp.round(s32).astype(dt)
                               if _is_int_dtype(dt)
                               else s32.astype(dt))
            return params, stats, nvel

        # donate the consumed accumulators and the replaced velocity;
        # base is read-only (it seeds the NEXT round's shadow compare)
        from split_learning_tpu.runtime.memo import bounded_setdefault
        return bounded_setdefault(
            self._fused_cache, self._fused_cache_max, sig,
            lambda: jax.jit(fused, donate_argnums=(0, 1, 3)))

    def stage_update(self, st: "_StageFold", base_flat, velocity,
                     momentum: float):
        """Dispatch one stage's fused sharded update; returns a pending
        handle whose :meth:`stage_fetch` does the stage's ONE
        device->host fetch.  Dispatch is async — the caller can
        dispatch every stage first and then fetch in stage order, so
        stage k's fetch/encode overlaps stage k+1's device compute
        (the per-shard streaming the START fan-out consumes)."""
        mom_paths = frozenset(_mom_path_set(st, base_flat, momentum))
        vels = _stage_velocity(st, base_flat, velocity, mom_paths)
        dtypes = dict(st.dtype)
        stat_dtypes = dict(st.stat_dtype)
        sig = (tuple(sorted((p, tuple(np.shape(a)), str(dtypes[p]))
                            for p, a in st.acc.items())),
               tuple(sorted((p, tuple(np.shape(a)),
                             str(stat_dtypes[p]))
                            for p, a in st.stat_acc.items())),
               tuple(sorted(mom_paths)))
        prog = self._fused_update(sig, dtypes, stat_dtypes, mom_paths)
        base_dev = {p: self._put(np.asarray(base_flat[p], np.float32))
                    for p in mom_paths}
        vel_dev = {}
        for p in mom_paths:
            v = vels[p]
            if v is None:
                vel_dev[p] = self._put(
                    np.zeros(np.shape(base_flat[p]), np.float32))
            elif isinstance(v, np.ndarray):
                vel_dev[p] = self._put(v)
            else:
                vel_dev[p] = v   # already device-resident (sharded)
        import warnings
        with warnings.catch_warnings():
            # int leaves accumulate in f32 and cast to int on output —
            # their donated buffer can't alias the narrower result, and
            # XLA says so once per compile; expected, not actionable
            warnings.filterwarnings(
                "ignore", message=".*donated buffers were not usable.*")
            params, stats, nvel = prog(
                dict(st.acc), dict(st.stat_acc), base_dev, vel_dev,
                np.float32(st.total_w), np.float32(st.stat_total_w),
                np.float32(momentum))
        st.acc = {}          # donated — the buffers are gone
        st.stat_acc = {}
        return params, stats, nvel

    def stage_fetch(self, pending):
        """The stage's single device->host fetch (params + stats in one
        transfer); the new velocity stays device-resident between
        rounds (the backend's sharded representation)."""
        params, stats, nvel = pending
        host_p, host_s = self._jax.device_get((params, stats))
        return host_p, host_s, nvel

    def _put(self, a: np.ndarray):
        return self._jax.device_put(a, self._sharding(a.shape))

    def contrib(self, leaf, w):
        a = np.asarray(leaf)
        return self._contrib(self._put(a), np.float32(w))

    def ingest(self, sums_leaf):
        # nan_to_num for wire-borne partial sums, like the host twin
        return self._put(np.nan_to_num(
            np.asarray(sums_leaf, dtype=np.float32)))

    def add(self, acc, t):
        return self._add(acc, t)

    def finalize(self, acc, total_w: float, dtype) -> np.ndarray:
        fn = self._div_round if _is_int_dtype(dtype) else self._div
        out = fn(acc, np.float32(total_w))
        return np.asarray(self._jax.device_get(out)).astype(dtype)

    def momentum_step(self, base, avg32, vel, m: float):
        b = self._put(np.asarray(base, dtype=np.float32))
        a = avg32 if not isinstance(avg32, np.ndarray) else self._put(avg32)
        if vel is None:
            vel = self._put(np.zeros(np.shape(base), np.float32))
        return self._mom(b, a, vel, np.float32(m))

    def to_host(self, x) -> np.ndarray:
        return np.asarray(self._jax.device_get(x))

    def nbytes(self, x) -> int:
        return int(np.prod(np.shape(x), dtype=np.int64)
                   * np.dtype(np.float32).itemsize)


def make_fold_backend(cfg) -> HostFoldBackend | MeshFoldBackend:
    if getattr(cfg.aggregation, "sharded", False):
        return MeshFoldBackend(kernels=getattr(cfg, "kernels", None))
    return HostFoldBackend()


# --------------------------------------------------------------------------
# tree flatten helpers: the canonical walk/unflatten live in
# ops/fedavg.py (imported above as _flat_items/_unflatten) — ONE copy
# of the dict-pytree semantics, shared with the TreeFold oracle, so
# the bit-identity contract cannot be broken by the two folds
# disagreeing about what a leaf is.
# --------------------------------------------------------------------------

def _tree_nbytes(tree) -> int:
    return sum(np.asarray(leaf).nbytes for _, leaf in _flat_items(tree))


# --------------------------------------------------------------------------
# the streaming fold
# --------------------------------------------------------------------------

class _StageFold:
    """Per-stage canonical-order fold state."""

    def __init__(self, order: list):
        self.order = list(order)          # canonical fold order (keys)
        self.order_set = set(self.order)
        self.next = 0                     # next canonical position
        self.pending: dict = {}           # key -> held contribution
        self.extras: dict = {}            # keys outside the plan
        self.folded: set = set()
        self.gone: set = set()            # dropped; stop waiting for them
        self.acc: dict = {}               # path -> backend accumulator
        self.dtype: dict = {}             # path -> original np dtype
        self.total_w: float = 0.0
        self.stat_acc: dict = {}
        self.stat_dtype: dict = {}
        self.stat_total_w: float = 0.0


class StreamingFold:
    """Incremental per-stage weighted FedAvg with a canonical-order
    reorder window (module docstring has the determinism contract).

    ``expected`` maps stage -> the ordered list of contribution keys
    (client ids, or group keys at an aggregator-tree root).  Duplicate
    contributions for a key are dropped and counted (``agg_dup_drops``)
    — at-least-once delivery must not double-weight a client.
    Thread-safe (the rpc pump and L1 threads may race an exporter).
    """

    def __init__(self, expected: dict, *, backend=None, faults=None,
                 hists=None):
        self.backend = backend if backend is not None else HostFoldBackend()
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        self.hists = hists
        self._lock = threading.Lock()
        self._stages = {int(s): _StageFold(keys)
                        for s, keys in expected.items()}
        self.n_samples = 0
        self.fold_s = 0.0
        self.folded = 0
        self.partials = 0
        self._held_bytes = 0
        self._held_hwm_bytes = 0
        self.window_hwm = 0
        self._finished = None

    # -- ingest --------------------------------------------------------------

    def add_update(self, u: Update, *, scale: float = 1.0,
                   key: str | None = None) -> None:
        """Fold one client Update (params may be None — a weight-less
        update occupies its canonical slot, counts stage-1 samples, and
        contributes nothing, exactly like the barrier oracle skips it).

        ``scale`` multiplies the FedAvg weight — the async mode's
        staleness decay (``staleness_decay ** version_lag``); 1.0 (the
        sync default) keeps the integer weight path bit-identical to
        the barrier oracle.  ``key`` overrides the fold key: a
        stale-admitted contribution folds under ``client@vN`` so it
        can never collide with (or dup-drop) the same client's fresh
        contribution in the canonical window — it lands in the extras
        set and folds deterministically (sorted) at finish."""
        if getattr(u, "delta_base", None) is not None:
            raise ValueError(
                f"delta-encoded Update from {u.client_id} reached the "
                "streaming fold un-reconstructed")
        self._enqueue(int(u.stage), key or u.client_id, ("u", u, scale),
                      0 if u.params is None else _tree_nbytes(u.params))

    def add_partial(self, stage: int, key: str, sums, weight: float,
                    dtypes, stat_sums=None, stat_weight: float = 0.0,
                    stat_dtypes=None, n_samples: int = 0) -> None:
        """Fold one L1 aggregator's per-stage partial SUMS at the
        group's canonical position."""
        item = ("p", dict(sums=sums, weight=weight, dtypes=dtypes,
                          stat_sums=stat_sums, stat_weight=stat_weight,
                          stat_dtypes=stat_dtypes, n_samples=n_samples))
        self._enqueue(int(stage), key, item,
                      _tree_nbytes(sums) if sums else 0)

    def has_key(self, stage: int, key) -> bool:
        """True once the key is accounted for at this stage: folded,
        held in the window, an extra, or declared gone."""
        with self._lock:
            st = self._stages.get(int(stage))
            return st is not None and (
                key in st.folded or key in st.pending
                or key in st.extras or key in st.gone)

    def drop(self, stage: int, key: str) -> None:
        """The key will never contribute (client dropped at a barrier):
        stop holding the window for it."""
        with self._lock:
            st = self._stages.get(int(stage))
            if st is None:
                return
            st.gone.add(key)
            self._drain(st)

    def _enqueue(self, stage: int, key, item, nbytes: int) -> None:
        with self._lock:
            st = self._stages.get(stage)
            if st is None:
                # a stage outside the plan: fold deterministically at
                # finish (sorted), never silently dropped
                st = self._stages[stage] = _StageFold([])
            if key in st.folded or key in st.pending or key in st.extras:
                self.faults.inc("agg_dup_drops")
                return
            if key not in st.order_set or key in st.gone:
                # outside the plan, or a key the window already gave up
                # on (dropped at a barrier, then revived — e.g. an
                # async late-READY rejoin): the canonical window has
                # passed its slot, so fold it deterministically
                # (sorted) at finish instead of parking it in a
                # pending slot the drain will never reach
                st.extras[key] = item
            else:
                st.pending[key] = item
            self._held_bytes += nbytes
            self._held_hwm_bytes = max(self._held_hwm_bytes,
                                       self._held_bytes)
            self.window_hwm = max(
                self.window_hwm,
                sum(len(s.pending) + len(s.extras)
                    for s in self._stages.values()))
            self._drain(st)

    # -- canonical-order drain ----------------------------------------------

    def _drain(self, st: _StageFold) -> None:
        while st.next < len(st.order):
            k = st.order[st.next]
            item = st.pending.pop(k, None)
            if item is None:
                if k in st.gone or k in st.folded:
                    st.next += 1
                    continue
                return   # window holds until the predecessor lands
            self._fold_item(st, k, item)
            st.next += 1

    def _fold_item(self, st: _StageFold, key, item) -> None:
        t0 = time.perf_counter()
        kind, payload = item[0], item[1]
        if kind == "u":
            scale = item[2] if len(item) > 2 else 1.0
            self._fold_update_item(st, payload, scale)
        else:
            self._fold_partial_item(st, payload)
        st.folded.add(key)
        self.folded += 1
        dt = time.perf_counter() - t0
        self.fold_s += dt
        if self.hists is not None:
            self.hists.observe("agg_fold", dt)

    def _fold_update_item(self, st: _StageFold, u: Update,
                          scale: float = 1.0) -> None:
        if u.stage == 1:
            self.n_samples += u.num_samples
        if u.params is None:
            return
        self._held_bytes -= _tree_nbytes(u.params)
        # sync path keeps the INT weight so the float summation is
        # bit-identical to the barrier oracle; the async staleness
        # decay scales it only when it actually decays
        w = max(1, u.num_samples)
        if scale != 1.0:
            w = w * float(scale)
        st.total_w += w
        be = self.backend
        for path, leaf in _flat_items(u.params):
            c = be.contrib(leaf, w)
            if path in st.acc:
                st.acc[path] = be.add(st.acc[path], c)
            else:
                st.acc[path] = c
                st.dtype[path] = np.asarray(leaf).dtype
        if u.batch_stats:
            st.stat_total_w += w
            for path, leaf in _flat_items(u.batch_stats):
                c = be.contrib(leaf, w)
                if path in st.stat_acc:
                    st.stat_acc[path] = be.add(st.stat_acc[path], c)
                else:
                    st.stat_acc[path] = c
                    st.stat_dtype[path] = np.asarray(leaf).dtype

    def _fold_partial_item(self, st: _StageFold, p: dict) -> None:
        self.partials += 1
        self.n_samples += int(p.get("n_samples") or 0)
        be = self.backend
        for acc, dty, sums_key, dt_key, w_key in (
                (st.acc, st.dtype, "sums", "dtypes", "weight"),
                (st.stat_acc, st.stat_dtype, "stat_sums", "stat_dtypes",
                 "stat_weight")):
            sums = p.get(sums_key)
            if not sums:
                continue
            if sums_key == "sums":
                self._held_bytes -= _tree_nbytes(sums)
                st.total_w += float(p[w_key])
            else:
                st.stat_total_w += float(p[w_key])
            dtypes = {path: np.dtype(d)
                      for path, d in _flat_items(p.get(dt_key) or {})}
            for path, leaf in _flat_items(sums):
                t = be.ingest(leaf)
                if path in acc:
                    acc[path] = be.add(acc[path], t)
                else:
                    acc[path] = t
                    dty[path] = dtypes.get(path, np.dtype(np.float32))

    def _drain_all(self) -> None:
        for st in self._stages.values():
            st.gone |= set(st.order)      # stop waiting; fold arrivals
            self._drain(st)
            for k in sorted(st.extras, key=str):
                self._fold_item(st, k, st.extras.pop(k))

    # -- results -------------------------------------------------------------

    def partial(self) -> tuple[dict, int]:
        """L1 flush: per-stage weighted SUMS (host np) + metadata, no
        divide — the root continues the fold.  Terminal."""
        with self._lock:
            self._drain_all()
            out: dict = {}
            be = self.backend
            for s in sorted(self._stages):
                st = self._stages[s]
                if not st.acc and not st.stat_acc and not st.total_w:
                    continue
                out[s] = {
                    "sums": _unflatten({p: be.to_host(a)
                                        for p, a in st.acc.items()}),
                    "weight": st.total_w,
                    "dtypes": _unflatten({p: str(d)
                                          for p, d in st.dtype.items()}),
                    "stat_sums": _unflatten(
                        {p: be.to_host(a)
                         for p, a in st.stat_acc.items()}),
                    "stat_weight": st.stat_total_w,
                    "stat_dtypes": _unflatten(
                        {p: str(d) for p, d in st.stat_dtype.items()}),
                }
            return out, self.n_samples

    def finish(self, base=None, momentum: float = 0.0,
               velocity: dict | None = None, *, fused: bool = True,
               on_stage=None) -> FoldResult:
        """The round-boundary update: FedAvg divide (+ optional server
        momentum vs ``base``) + cast back to each leaf's START wire
        dtype, in canonical stage order; idempotent (returns the first
        result).

        ``fused`` (``aggregation.update-sharded``, default) runs each
        stage's whole update as ONE backend program — on the mesh
        backend a jitted, donated, leaf-axis-0-sharded program whose
        result comes back in a single device->host fetch; every
        stage's program is dispatched before any stage is fetched, so
        stage k's fetch (and whatever the caller's ``on_stage``
        does with it — shadow refresh, START encode) overlaps stage
        k+1's device compute.  ``fused=False`` keeps the legacy
        per-leaf path as the bit-parity oracle.

        ``on_stage(stage, stage_params, stage_stats)`` (when given) is
        called per stage, in ascending stage order, the moment that
        stage's host trees exist — the per-shard streaming hook the
        server's START fan-out consumes."""
        with self._lock:
            if self._finished is not None:
                return self._finished
            self._drain_all()
            be = self.backend
            t0 = time.perf_counter()
            params: dict = {}
            stats: dict = {}
            stage_ms: dict = {}
            base_flat = (dict(_flat_items(base))
                         if (momentum and base is not None) else None)
            order = [s for s in sorted(self._stages)
                     if self._stages[s].acc or self._stages[s].stat_acc]
            if fused:
                # all stages dispatch BEFORE any stage fetches; sound
                # because stage param paths are disjoint (stage
                # concatenation of absolute layer keys) — no stage's
                # velocity read depends on another stage's write
                pending = [(s, be.stage_update(self._stages[s],
                                               base_flat, velocity,
                                               momentum))
                           for s in order]
                for s, pend in pending:
                    t_s = time.perf_counter()
                    flat_p, flat_s, new_vel = be.stage_fetch(pend)
                    if velocity is not None:
                        velocity.update(new_vel)
                    stage_p = _unflatten(flat_p)
                    stage_s = _unflatten(flat_s)
                    params.update(stage_p)
                    stats.update(stage_s)
                    stage_ms[s] = round(
                        (time.perf_counter() - t_s) * 1e3, 3)
                    if on_stage is not None:
                        on_stage(s, stage_p, stage_s)
            else:
                for s in order:
                    t_s = time.perf_counter()
                    st = self._stages[s]
                    flat: dict = {}
                    for path, acc in st.acc.items():
                        dt = st.dtype[path]
                        if base_flat is not None and path in base_flat \
                                and not _is_int_dtype(dt):
                            # server momentum (FedAvgM): average in
                            # f32, optimizer step in the backend, one
                            # dtype cast at the end
                            avg32 = be.finalize(acc, st.total_w,
                                                np.dtype(np.float32))
                            vel = (velocity or {}).get(path)
                            if vel is not None and np.shape(vel) != \
                                    np.shape(base_flat[path]):
                                # an elastic re-plan moved this path's
                                # layer range: the old velocity is
                                # another tensor's momentum — restart
                                # from zero
                                vel = None
                            new32, nv = be.momentum_step(
                                base_flat[path], avg32, vel, momentum)
                            if velocity is not None:
                                velocity[path] = nv
                            flat[path] = be.to_host(new32).astype(dt)
                        else:
                            flat[path] = be.finalize(acc, st.total_w,
                                                     dt)
                    stage_p = _unflatten(flat)
                    stage_s = {}
                    if st.stat_acc:
                        stage_s = _unflatten(
                            {p: be.finalize(a, st.stat_total_w,
                                            st.stat_dtype[p])
                             for p, a in st.stat_acc.items()})
                    params.update(stage_p)
                    stats.update(stage_s)
                    stage_ms[s] = round(
                        (time.perf_counter() - t_s) * 1e3, 3)
                    if on_stage is not None:
                        on_stage(s, stage_p, stage_s)
            update_s = time.perf_counter() - t0
            self.fold_s += update_s
            result_bytes = _tree_nbytes(params)
            peak = (1.0 + self._held_hwm_bytes / result_bytes
                    if result_bytes else float(bool(self.window_hwm)))
            self._finished = FoldResult(
                params=params, stats=stats, n_samples=self.n_samples,
                fold_s=round(self.fold_s, 6),
                peak_tree_copies=round(peak, 3),
                window_hwm=self.window_hwm, folded=self.folded,
                partials=self.partials,
                update_s=round(update_s, 6), stage_update_ms=stage_ms)
            return self._finished


def plan_fanin_groups(active: list, fan_in: int) -> list:
    """Partition the round's (client_id, stage) send set into L1
    aggregator groups of at most ``fan_in`` clients, per stage (a group
    never spans stages — its partial covers one stage's key slice), in
    canonical sorted order.  Returns ``[AggGroup]``."""
    by_stage: dict[int, list] = {}
    for cid, s in active:
        by_stage.setdefault(int(s), []).append(cid)
    groups: list[AggGroup] = []
    gi = 0
    for s in sorted(by_stage):
        cids = sorted(by_stage[s])
        for i in range(0, len(cids), fan_in):
            groups.append(AggGroup(idx=gi, stage=s,
                                   members=cids[i:i + fan_in]))
            gi += 1
    return groups


def plan_tree(active: list, fan_in: int, levels: int = 1) -> list:
    """:func:`plan_fanin_groups` generalized to a recursive tree
    (``aggregation.levels``): level-1 groups fold ≤ ``fan_in`` client
    Updates; each higher level folds ≤ ``fan_in`` child-group
    PARTIALS (sums of sums, total weight carried, so any depth still
    divides exactly once at the root).  Group indices are globally
    unique across levels — a group's input queue is simply
    ``aggregate_queue(cluster, idx)`` whatever its level.  A stage
    whose level-k population is already a single group is NOT wrapped
    again (a one-child interior node would add a hop for nothing), so
    such a group stays parentless (``parent is None`` = publish to
    the root's rpc queue).  Returns every group of every level,
    canonical order within each level.
    """
    groups = plan_fanin_groups(active, fan_in)
    gi = len(groups)
    tier = groups
    for _ in range(2, levels + 1):
        by_stage: dict[int, list] = {}
        for g in tier:
            by_stage.setdefault(g.stage, []).append(g)
        nxt: list[AggGroup] = []
        for s in sorted(by_stage):
            kids = sorted(by_stage[s], key=lambda g: g.idx)
            if len(kids) <= 1:
                continue   # nothing to reduce at this stage
            for i in range(0, len(kids), fan_in):
                chunk = kids[i:i + fan_in]
                parent = AggGroup(
                    idx=gi, stage=s,
                    members=[c.key for c in chunk],
                    level=chunk[0].level + 1)
                gi += 1
                for c in chunk:
                    c.parent = parent.idx
                nxt.append(parent)
        if not nxt:
            break
        groups += nxt
        tier = nxt
    return groups


def root_groups(groups: list) -> list:
    """The parentless groups — whose PartialAggregates land at the
    server root (canonical order: level then idx)."""
    return sorted((g for g in groups if g.parent is None),
                  key=lambda g: g.idx)


def group_key(idx: int) -> str:
    """Canonical fold key of aggregator group ``idx`` (zero-padded so
    lexicographic order == numeric order)."""
    return f"g{idx:05d}"


@dataclasses.dataclass
class AggGroup:
    idx: int
    stage: int
    members: list               # client ids (level 1) or child keys
    level: int = 1
    parent: int | None = None   # parent group idx; None = root child

    @property
    def key(self) -> str:
        return group_key(self.idx)

    def as_dict(self) -> dict:
        """Wire form for :class:`~split_learning_tpu.runtime.protocol
        .AggAssign` (plain builtins — the restricted unpickler's
        vocabulary stays closed)."""
        return {"idx": self.idx, "stage": self.stage,
                "members": list(self.members), "level": self.level,
                "parent": self.parent}

    @classmethod
    def from_dict(cls, d: dict) -> "AggGroup":
        return cls(idx=int(d["idx"]), stage=int(d["stage"]),
                   members=list(d.get("members") or []),
                   level=int(d.get("level", 1)),
                   parent=d.get("parent"))


# --------------------------------------------------------------------------
# L1 aggregator
# --------------------------------------------------------------------------

class L1Aggregator(threading.Thread):
    """One aggregator-tree interior node: drains its group's
    ``aggregate_queue``, folds its members in canonical member order,
    and publishes one PartialAggregate to ``out_queue`` — the server's
    rpc queue for a parentless group, the parent group's aggregate
    queue below an L2 (``aggregation.levels``).  A level-1 node folds
    client Updates; a level ≥ 2 node folds its children's
    PartialAggregates (sums of sums, total weight carried).

    ``codec`` (a ``transport.codec: partial`` spec) compresses the
    published sums (``runtime/codec/partial.py``); ``base``/
    ``base_gen`` are the stage's START shard for the delta mode — an
    interior node uses the same base to DECODE codec'd child partials.

    Flushes when every expected member has folded, on
    :meth:`request_flush` (the server gave up on stragglers), or at
    ``deadline``.  ``TEST_KILL`` (a set of aggregator names) makes the
    thread die silently mid-round — the failure-injection hook the
    direct-to-root fallback tests use.
    """

    TEST_KILL: set = set()

    def __init__(self, bus, *, cluster: int, group: AggGroup,
                 members: list, gen: int, deadline: float,
                 log=None, faults=None, chunk_bytes: int | None = None,
                 owns_bus: bool = False, out_queue: str = RPC_QUEUE,
                 codec=None, base=None, base_gen: int | None = None):
        self.agg_id = f"aggregator_{cluster}_{group.idx}"
        super().__init__(daemon=True, name=self.agg_id)
        self.bus = bus
        self.cluster = cluster
        self.group = group
        self.members = list(members)
        self.gen = gen
        self.deadline = deadline
        self.log = log
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        self.chunk_bytes = chunk_bytes
        self.owns_bus = owns_bus
        self.out_queue = out_queue
        self.codec = codec
        self.base = base
        self.base_gen = base_gen
        self.flushed = False
        self._flush = threading.Event()
        self._kill = threading.Event()
        # per-group fold state lives on the INSTANCE so a standalone
        # aggregator node (runtime/aggnode.py) can drive the same
        # object directly — feed_raw()/publish() without start()ing
        # the thread — and the thread run loop is just a driver
        self.fold = StreamingFold({self.group.stage: self.members},
                                  faults=self.faults)
        self.asm = FrameAssembler(faults=self.faults)
        self.meta: list[dict] = []
        self.seen: set = set()
        self.ingress_bytes = 0
        self.egress_bytes = 0

    def request_flush(self) -> None:
        self._flush.set()

    def kill(self) -> None:
        """Die without flushing (tests: the L1-failure path)."""
        self._kill.set()

    @property
    def complete(self) -> bool:
        return self.seen >= set(self.members)

    @property
    def queue(self) -> str:
        return aggregate_queue(self.cluster, self.group.idx)

    def run(self) -> None:
        try:
            while True:
                if self._kill.is_set() \
                        or self.agg_id in L1Aggregator.TEST_KILL:
                    return   # died mid-round: the server's fallback
                    # drains the queue direct-to-root
                raw = self.bus.get(self.queue, timeout=0.2)
                if raw is not None:
                    self.feed_raw(raw)
                if self.complete or self._flush.is_set() \
                        or time.monotonic() >= self.deadline:
                    self.publish()
                    return
        finally:
            if self.owns_bus:
                try:
                    self.bus.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass

    def feed_raw(self, raw: bytes) -> None:
        self.ingress_bytes += len(raw)
        try:
            msg = self.asm.feed(raw)
        except Exception as e:  # noqa: BLE001 — one corrupt frame must
            # cost one message, not the aggregator
            self.faults.inc("corrupt_rejected")
            if self.log is not None:
                self.log.warning(f"{self.agg_id}: dropping undecodable "
                                 f"frame: {e}")
            return
        if msg is None:
            return
        if isinstance(msg, Update) and self.group.level == 1:
            self._feed_update(msg)
        elif isinstance(msg, PartialAggregate) and self.group.level > 1:
            self._feed_partial(msg)

    def _feed_update(self, msg: Update) -> None:
        if msg.round_idx != self.gen:
            self.faults.inc("agg_stale_drops")
            return
        if msg.client_id in self.seen:
            self.faults.inc("agg_dup_drops")
            return
        self.seen.add(msg.client_id)
        self.fold.add_update(msg)
        self.meta.append(
            {"client_id": msg.client_id, "stage": msg.stage,
             "num_samples": msg.num_samples, "ok": msg.ok,
             "telemetry": msg.telemetry})
        if self.log is not None:
            self.log.received(f"UPDATE {msg.client_id} (L1 fold)")

    def _feed_partial(self, msg: PartialAggregate) -> None:
        """Interior-level ingest: one child group's partial, dedup'd on
        its key like a level-1 member Update — the at-least-once wire
        must not double-weight a whole group either."""
        if msg.round_idx != self.gen:
            self.faults.inc("agg_stale_drops")
            return
        key = group_key(msg.group)
        if key in self.seen:
            self.faults.inc("agg_dup_drops")
            return
        if msg.codec or msg.members_z:
            from split_learning_tpu.runtime.codec.partial import (
                PartialCodecError, decode_partial_msg,
            )
            try:
                decode_partial_msg(
                    msg, bases={msg.stage: self.base},
                    base_gen=self.base_gen)
            except PartialCodecError as e:
                self.faults.inc("partial_codec_errors")
                if self.log is not None:
                    self.log.warning(f"{self.agg_id}: dropping "
                                     f"undecodable partial: {e}")
                return
        self.seen.add(key)
        self.fold.add_partial(msg.stage, key, msg.sums, msg.weight,
                              msg.dtypes, stat_sums=msg.stat_sums,
                              stat_weight=msg.stat_weight,
                              stat_dtypes=msg.stat_dtypes,
                              n_samples=msg.n_samples)
        self.meta.extend(msg.members or [])
        if self.log is not None:
            self.log.received(f"PARTIALAGGREGATE {msg.aggregator_id} "
                              f"(L{self.group.level} fold)")

    def publish(self) -> int:
        """Flush: one PartialAggregate (codec'd when configured) to
        ``out_queue``; returns the published wire bytes.  Idempotent —
        a second call is a no-op (0 bytes)."""
        if self.flushed:
            return 0
        stages, n_samples = self.fold.partial()
        ent = stages.get(self.group.stage, {})
        codec_s = codec_base = members_z = None
        members = self.meta
        if self.codec is not None:
            if ent.get("sums"):
                from split_learning_tpu.runtime.codec.partial import (
                    encode_partial_entry,
                )
                ent, codec_s, codec_base = encode_partial_entry(
                    ent, self.codec, base=self.base,
                    base_gen=self.base_gen, faults=self.faults)
            # the member metadata is the OTHER O(clients) term of a
            # root partial's bytes — pack it with the sums
            from split_learning_tpu.runtime.protocol import (
                pack_members,
            )
            members_z = pack_members(members)
            if members_z is not None:
                members = None
        msg = PartialAggregate(
            aggregator_id=self.agg_id, cluster=self.cluster,
            group=self.group.idx, stage=self.group.stage,
            round_idx=self.gen, sums=ent.get("sums"),
            weight=float(ent.get("weight") or 0.0),
            dtypes=ent.get("dtypes"), stat_sums=ent.get("stat_sums"),
            stat_weight=float(ent.get("stat_weight") or 0.0),
            stat_dtypes=ent.get("stat_dtypes"), n_samples=n_samples,
            members=members, level=self.group.level, codec=codec_s,
            codec_base=codec_base, members_z=members_z)
        nbytes = 0
        for part in encode_parts(msg, self.chunk_bytes):
            self.bus.publish(self.out_queue, part)  # slcheck: wire=PartialAggregate
            nbytes += len(part)
        self.egress_bytes += nbytes
        self.flushed = True
        if self.log is not None:
            self.log.sent(f"PARTIALAGGREGATE members={len(self.meta)}/"
                          f"{len(self.members)}")
        return nbytes


def drain_group_queue(bus, cluster: int, group_idx: int, gen: int,
                      assembler: FrameAssembler, faults,
                      log=None) -> list:
    """Direct-to-root fallback: drain whatever a dead (or flushed)
    aggregator's queue currently holds and return the fresh-generation
    messages — member Updates for a level-1 group, child
    PartialAggregates for an interior one — so the root can fold the
    members itself."""
    out: list = []
    while True:
        q = aggregate_queue(cluster, group_idx)
        raw = bus.get(q, timeout=0.0)
        if raw is None:
            return out
        try:
            msg = assembler.feed(raw)
        except Exception as e:  # noqa: BLE001 — count and continue
            faults.inc("corrupt_rejected")
            if log is not None:
                log.warning(f"fallback drain: undecodable frame: {e}")
            continue
        if msg is None or not isinstance(msg, (Update, PartialAggregate)):
            continue
        if msg.round_idx != gen:
            faults.inc("agg_stale_drops")
            continue
        out.append(msg)
