"""Per-process flight recorder: bounded event ring + crash dumps.

Every chaos cell in this repo kills processes on purpose (SIGKILL'd
aggregators, shard kills, stage-host kills), and a real fleet kills
them by accident — yet the only evidence a death leaves is monotonic
fault counters and whatever ``app.log`` lines got flushed.  This module
is the missing bounded recent-history capture:

* :class:`BlackboxRing` — a lock-cheap bounded ring
  (``collections.deque(maxlen=ring_events)`` under one mutex) that the
  existing instrumentation seams feed: span open/close
  (``runtime/spans.py``), frame publish/consume metadata (``bus.py``
  transports), scheduler decisions, fault-counter increments
  (``runtime/trace.py``), chaos injections (``runtime/chaos.py``).
  Recording is a dict build + deque append; a disabled ring costs one
  attribute read.
* :func:`install` — wires the process for *abnormal-exit* capture:
  SIGTERM/SIGABRT handlers, a chained ``sys.excepthook``, and a
  chained ``threading.excepthook`` all flush an atomic
  ``blackbox-{participant}.json`` dump before the process unwinds.
  Handlers chain to whatever was installed before (broker shards
  already trap SIGTERM for a clean exit) and re-deliver the default
  disposition otherwise, so exit codes stay honest.
* :func:`dump` — atomic (tempfile + ``os.replace``) JSON snapshot:
  header (participant, role, pid, reason, wall time, event seq) first,
  then the ring events oldest-first, then a fault-counter snapshot.
  Dumps also fire on demand: the protocol server fans out a
  ``BlackboxDump`` control frame when any participant dies, so one
  death snapshots the whole fleet's last N seconds.
* :func:`load_dump` — scavenge-tolerant loader (same discipline as
  ``sl_perf``'s BENCH loader): a torn or truncated dump — a process
  killed mid-``os.replace`` predecessor, a copied partial file —
  yields the header fields plus every event that parses, flagged
  ``torn``, instead of raising out of the postmortem assembler.

SIGKILL is uncatchable by design: the killed process writes nothing,
and that absence is itself evidence — ``tools/sl_postmortem.py`` names
the victim from the *surviving* fleet's dumps (the server records
``participant_lost`` / ``child_exit`` events with the victim's role
and round).
"""

from __future__ import annotations

import collections
import json
import os
import pathlib
import re
import signal
import sys
import tempfile
import threading
import time
from typing import Any

#: blackbox dump schema version (bump on breaking change)
SCHEMA_VERSION = 1

#: event kinds sl_postmortem treats as abnormal (ordered by severity
#: only for tie-breaks at equal timestamps; the FIRST one on the
#: merged timeline is the proximate cause)
ABNORMAL_KINDS = ("signal", "exception", "chaos_crash",
                  "participant_lost", "child_exit", "shard_dead")


class BlackboxRing:
    """Bounded in-memory event ring for one process.

    ``record`` is the only hot-path entry point: one lock, one dict,
    one deque append (the deque evicts the oldest event itself).
    ``seq`` counts every event ever recorded, so a dump can report how
    many were overwritten (``seq - len(events)``)."""

    def __init__(self, maxlen: int = 2048, enabled: bool = True):
        self.enabled = enabled
        self.maxlen = int(maxlen)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.maxlen)
        self.seq = 0
        self.participant = ""
        self.role = ""
        self.dump_dir: pathlib.Path | None = None
        self.last_dump_t: float | None = None
        self.last_dump_path: pathlib.Path | None = None

    def record(self, kind: str, **attrs: Any) -> None:
        if not self.enabled:
            return
        ev = {"t": time.time(), "kind": kind}
        for k, v in attrs.items():
            if v is not None:
                ev[k] = v
        with self._lock:
            self.seq += 1
            ev["seq"] = self.seq
            self._ring.append(ev)

    def depth(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> tuple[list[dict], int]:
        """(events oldest-first, total seq) — a consistent pair."""
        with self._lock:
            return list(self._ring), self.seq

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


#: the process-wide default ring every seam records into.  Starts
#: disabled so library import costs nothing; :func:`configure` /
#: :func:`install` turn it on from config at each entry point.
_RING = BlackboxRing(enabled=False)
_install_lock = threading.Lock()
_installed = False


def ring() -> BlackboxRing:
    return _RING


def record(kind: str, **attrs: Any) -> None:
    """Record one event into the process ring (no-op when disabled)."""
    if _RING.enabled:
        _RING.record(kind, **attrs)


def enabled() -> bool:
    return _RING.enabled


def depth() -> int:
    return _RING.depth() if _RING.enabled else 0


def last_dump_age() -> float | None:
    t = _RING.last_dump_t
    return None if t is None else max(0.0, time.time() - t)


def configure(cfg, participant: str, role: str = "") -> BlackboxRing:
    """Size + aim the process ring from ``cfg.observability.blackbox``.

    ``cfg`` may be a full Config, an ObservabilityConfig-less stub, or
    None (broker shards configure via :func:`configure_basic`) — the
    recorder degrades to disabled, never raises, because it runs at
    every process entry point including half-configured test rigs."""
    obs = getattr(cfg, "observability", None)
    bb = getattr(obs, "blackbox", None) if obs is not None else None
    if bb is None or not getattr(bb, "enabled", False):
        _RING.enabled = False
        return _RING
    dump_dir = getattr(bb, "dump_dir", None)
    if dump_dir is None:
        # land dumps next to the run's other artifacts (spans/metrics)
        # so one directory holds everything sl_postmortem needs
        journal = getattr(obs, "journal_dir", None) \
            or getattr(cfg, "log_path", ".")
        dump_dir = journal
        if getattr(obs, "run_scoped", False):
            try:
                from split_learning_tpu.runtime.log import run_output_dir
                dump_dir = run_output_dir(pathlib.Path(journal))
            except Exception:
                pass
    return configure_basic(participant, role=role,
                           dump_dir=dump_dir,
                           ring_events=getattr(bb, "ring_events", 2048))


def configure_basic(participant: str, role: str = "",
                    dump_dir: str | pathlib.Path | None = None,
                    ring_events: int = 2048) -> BlackboxRing:
    """Config-less twin of :func:`configure` for processes that never
    load a Config (broker shards get argv, not YAML)."""
    if _RING.maxlen != int(ring_events):
        _RING.maxlen = int(ring_events)
        with _RING._lock:
            _RING._ring = collections.deque(_RING._ring,
                                            maxlen=_RING.maxlen)
    _RING.participant = participant
    _RING.role = role or _infer_role(participant)
    _RING.dump_dir = (pathlib.Path(dump_dir) if dump_dir is not None
                      else None)
    _RING.enabled = True
    return _RING


def _infer_role(participant: str) -> str:
    p = participant.lower()
    if p.startswith("client"):
        return "client"
    if p.startswith(("agg", "node")):
        return "agg_node"
    if p.startswith(("host", "stage")):
        return "stage_host"
    if p.startswith("broker"):
        return "broker_shard"
    if p.startswith("server"):
        return "server"
    return participant or "?"


# -- dumps ------------------------------------------------------------------

def dump(reason: str, path: str | pathlib.Path | None = None,
         extra: dict | None = None) -> pathlib.Path | None:
    """Atomically write ``blackbox-{participant}.json``; returns the
    path (None when the recorder is disabled or the write failed — a
    dump must never take the process down with it)."""
    if not _RING.enabled:
        return None
    events, seq = _RING.snapshot()
    doc: dict[str, Any] = {
        "v": SCHEMA_VERSION,
        "participant": _RING.participant or "?",
        "role": _RING.role or "?",
        "pid": os.getpid(),
        "reason": reason,
        "t_dump": time.time(),
        "seq": seq,
        "dropped": max(0, seq - len(events)),
    }
    if extra:
        doc.update(extra)
    try:
        from split_learning_tpu.runtime.trace import (
            default_fault_counters,
        )
        doc["faults"] = dict(default_fault_counters.snapshot())
    except Exception:
        doc["faults"] = {}
    # events LAST: a torn write still yields a parseable header for
    # the scavenge loader
    doc["events"] = events
    if path is None:
        d = _RING.dump_dir or pathlib.Path(".")
        path = pathlib.Path(d) / f"blackbox-{_RING.participant or os.getpid()}.json"
    path = pathlib.Path(path)
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=path.name + ".",
                                   dir=str(path.parent))
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, default=_json_default)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception:
        return None
    _RING.last_dump_t = time.time()
    _RING.last_dump_path = path
    return path


def dump_bytes(reason: str, extra: dict | None = None,
               participant: str | None = None) -> bytes:
    """The dump document serialized in-memory (no file): how broker
    shards answer the ``__broker__.blackbox`` control queue — the
    requester owns the dump directory, not the shard."""
    events, seq = _RING.snapshot()
    doc = {"v": SCHEMA_VERSION,
           "participant": participant or _RING.participant or "?",
           "role": _RING.role or (
               "broker_shard" if participant else "?"),
           "pid": os.getpid(), "reason": reason,
           "t_dump": time.time(), "seq": seq,
           "dropped": max(0, seq - len(events))}
    if extra:
        doc.update(extra)
    doc["events"] = events
    return json.dumps(doc, default=_json_default).encode()


def write_dump_dict(doc: dict, dump_dir: str | pathlib.Path | None = None
                    ) -> pathlib.Path | None:
    """Atomically persist a dump document fetched from a REMOTE ring
    (a broker shard's ``__broker__.blackbox`` reply) next to this
    process's own dumps.  Same never-raise contract as :func:`dump`."""
    name = str(doc.get("participant") or "remote")
    name = re.sub(r"[^A-Za-z0-9_.@-]", "_", name)
    d = dump_dir if dump_dir is not None \
        else (_RING.dump_dir or pathlib.Path("."))
    path = pathlib.Path(d) / f"blackbox-{name}.json"
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=path.name + ".",
                                   dir=str(path.parent))
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, default=_json_default)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception:
        return None
    return path


def _json_default(o):
    try:
        return str(o)
    except Exception:
        return "?"


# -- abnormal-exit handlers -------------------------------------------------

def install(cfg, participant: str, role: str = "") -> BlackboxRing:
    """Configure the ring AND arm the abnormal-exit capture: signal
    handlers (SIGTERM/SIGABRT), ``sys.excepthook`` and
    ``threading.excepthook``, each chaining to the previously
    installed one.  Idempotent; safe off the main thread (signal
    handlers are then skipped — Python only allows them on main)."""
    bb = configure(cfg, participant, role=role)
    if bb.enabled:
        _install_handlers()
    return bb


def install_basic(participant: str, role: str = "",
                  dump_dir: str | pathlib.Path | None = None,
                  ring_events: int = 2048) -> BlackboxRing:
    """Config-less :func:`install` (broker shards)."""
    bb = configure_basic(participant, role=role, dump_dir=dump_dir,
                         ring_events=ring_events)
    _install_handlers()
    return bb


def _install_handlers() -> None:
    global _installed
    with _install_lock:
        if _installed:
            return
        _installed = True
    prev_except = sys.excepthook

    def _hook(tp, val, tb):
        try:
            record("exception", type=tp.__name__, msg=str(val)[:200])
            dump(f"excepthook:{tp.__name__}")
        except Exception:
            pass
        prev_except(tp, val, tb)

    sys.excepthook = _hook

    prev_thread = threading.excepthook

    def _thook(args):
        try:
            if args.exc_type is not SystemExit:
                record("exception", type=args.exc_type.__name__,
                       msg=str(args.exc_value)[:200],
                       thread=getattr(args.thread, "name", "?"))
                dump(f"thread-excepthook:{args.exc_type.__name__}")
        except Exception:
            pass
        prev_thread(args)

    threading.excepthook = _thook

    if threading.current_thread() is not threading.main_thread():
        return
    for signame in ("SIGTERM", "SIGABRT"):
        signum = getattr(signal, signame, None)
        if signum is None:
            continue
        try:
            prev = signal.getsignal(signum)
            signal.signal(signum, _make_signal_handler(signame, signum,
                                                       prev))
        except (OSError, ValueError, RuntimeError):
            pass


def _make_signal_handler(signame: str, signum: int, prev):
    def _handler(sig, frame):
        try:
            record("signal", sig=signame)
            dump(f"signal:{signame}")
        except Exception:
            pass
        if callable(prev):
            prev(sig, frame)
            return
        if prev is signal.SIG_IGN:
            return
        # default disposition: re-deliver so the exit status reports
        # the real signal, not a python exception
        try:
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
        except (OSError, ValueError):
            sys.exit(128 + signum)
    return _handler


# -- scavenge-tolerant loader -----------------------------------------------

_HDR_KEYS = ("v", "participant", "role", "pid", "reason", "t_dump",
             "seq", "dropped")


def load_dump(path: str | pathlib.Path) -> dict | None:
    """Parse a blackbox dump, tolerating torn/truncated files.

    Returns the full document when it parses; otherwise scavenges the
    header fields by regex and every leading event object that still
    parses (``torn: true`` marks the salvage).  Returns None only when
    the file is unreadable or yields nothing at all."""
    try:
        text = pathlib.Path(path).read_text(errors="replace")
    except OSError:
        return None
    if not text.strip():
        return None
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            doc.setdefault("events", [])
            return doc
    except ValueError:
        pass
    out: dict[str, Any] = {"torn": True}
    for key in _HDR_KEYS:
        m = re.search(r'"%s"\s*:\s*("(?:[^"\\]|\\.)*"|-?[0-9.eE+]+)'
                      % re.escape(key), text)
        if m:
            try:
                out[key] = json.loads(m.group(1))
            except ValueError:
                pass
    events: list[dict] = []
    i = text.find('"events"')
    if i >= 0:
        i = text.find("[", i)
    if i >= 0:
        dec = json.JSONDecoder()
        j = i + 1
        n = len(text)
        while True:
            while j < n and text[j] in ", \t\r\n":
                j += 1
            if j >= n or text[j] != "{":
                break
            try:
                obj, j = dec.raw_decode(text, j)
            except ValueError:
                break
            if isinstance(obj, dict):
                events.append(obj)
    out["events"] = events
    if len(out) <= 2 and not events:
        return None
    return out


def find_dumps(root: str | pathlib.Path) -> list[pathlib.Path]:
    """Every ``blackbox-*.json`` under ``root`` (recursive, sorted)."""
    root = pathlib.Path(root)
    if root.is_file():
        return [root]
    return sorted(root.rglob("blackbox-*.json"))


def _reset_for_tests() -> None:
    """Test hook: forget installs/config so one process can exercise
    several configurations (handlers stay chained — harmless)."""
    global _installed
    _RING.enabled = False
    _RING.participant = ""
    _RING.role = ""
    _RING.dump_dir = None
    _RING.last_dump_t = None
    _RING.last_dump_path = None
    _RING.seq = 0
    _RING.clear()
    with _install_lock:
        _installed = False
