"""Runtime tracing: XLA profiler capture + per-phase step timing.

The reference's only observability is tqdm bars, per-batch loss prints,
and a one-shot message-size probe (SURVEY.md §5.1); its in-message
``trace`` field is routing state, not tracing.  Here:

* :class:`StepTimer` — named wall-clock phase accumulators with
  ``jax.block_until_ready`` fencing, dumped as a metrics dict (feeds the
  metrics.jsonl sidecar, ``runtime/log.py``);
* :func:`trace` — context manager around ``jax.profiler`` writing a
  TensorBoard-loadable XLA trace;
* :func:`annotate` — ``TraceAnnotation`` wrapper so host-side round
  phases (plan/train/aggregate/validate) show up on the trace timeline;
* :class:`FaultCounters` — thread-safe failure/recovery counters
  (``drops``, ``timeouts``, ``redeliveries``, ``dedup_hits``,
  ``reconnects``, ...) shared by the transport stack
  (``runtime/bus.py`` reliability layer, ``runtime/chaos.py`` fault
  injection, TCP reconnect) and surfaced by the protocol server into
  ``metrics.jsonl`` and its end-of-round log line, so chaos runs are
  observable instead of silently self-healing.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time

import jax


class FaultCounters:
    """Monotonic named counters; values never reset during a run, so
    consumers diff successive snapshots (same contract as the server's
    cumulative wire-byte metrics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: collections.Counter = collections.Counter()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] += n

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())


#: process-wide default registry: every transport wrapper created without
#: an explicit ``faults=`` lands here, so one process's server sees its
#: clients' counters too in single-process (inproc) deployments
default_fault_counters = FaultCounters()


class WireCounters:
    """Thread-safe wire-traffic counters (same monotonic contract as
    :class:`FaultCounters`: values never reset, consumers diff
    successive snapshots): bytes sent/received per queue, cumulative
    encode/decode seconds, and the async sender-queue high-water mark.
    Fed by the transport stack (``runtime/bus.py AsyncTransport``) and
    the protocol codec call sites; surfaced into ``metrics.jsonl`` by
    the server's end-of-round summary and each client's round-end
    record."""

    #: queue-name prefixes classified as data-plane traffic
    _DATA_PREFIXES = ("intermediate_queue", "gradient_queue")

    def __init__(self):
        self._lock = threading.Lock()
        self._bytes_out: collections.Counter = collections.Counter()
        self._bytes_in: collections.Counter = collections.Counter()
        self._msgs_out = 0
        self._msgs_in = 0
        self._encode_s = 0.0
        self._encode_n = 0
        self._decode_s = 0.0
        self._decode_n = 0
        self._send_queue_hwm = 0

    def count_out(self, queue: str, nbytes: int) -> None:
        with self._lock:
            self._bytes_out[queue] += nbytes
            self._msgs_out += 1

    def count_in(self, queue: str, nbytes: int) -> None:
        with self._lock:
            self._bytes_in[queue] += nbytes
            self._msgs_in += 1

    def add_encode(self, seconds: float) -> None:
        with self._lock:
            self._encode_s += seconds
            self._encode_n += 1

    def add_decode(self, seconds: float) -> None:
        with self._lock:
            self._decode_s += seconds
            self._decode_n += 1

    def note_send_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self._send_queue_hwm:
                self._send_queue_hwm = depth

    def per_queue(self) -> dict:
        with self._lock:
            return {"bytes_out": dict(self._bytes_out),
                    "bytes_in": dict(self._bytes_in)}

    def _data_bytes(self, counter) -> int:
        return sum(n for q, n in counter.items()
                   if q.startswith(self._DATA_PREFIXES))

    def snapshot(self) -> dict:
        """Flat record for metrics.jsonl (zero-valued fields included —
        callers prune)."""
        with self._lock:
            return {
                "bytes_out_total": sum(self._bytes_out.values()),
                "bytes_in_total": sum(self._bytes_in.values()),
                "data_bytes_out": self._data_bytes(self._bytes_out),
                "data_bytes_in": self._data_bytes(self._bytes_in),
                "msgs_out": self._msgs_out,
                "msgs_in": self._msgs_in,
                "encode_s": round(self._encode_s, 6),
                "encode_n": self._encode_n,
                "decode_s": round(self._decode_s, 6),
                "decode_n": self._decode_n,
                "send_queue_hwm": self._send_queue_hwm,
            }


#: process-wide default, mirroring ``default_fault_counters``
default_wire_counters = WireCounters()


class StepTimer:
    """Accumulates wall-clock per named phase; device-fenced."""

    def __init__(self):
        self.totals: dict = collections.defaultdict(float)
        self.counts: dict = collections.defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time a phase.  The context yields a ``fence`` callable: pass
        it the pytree produced INSIDE the block and it is blocked on
        before the clock stops, so async dispatch doesn't hide device
        time::

            with timer.phase("step") as fence:
                out = step(...)
                fence(out)
        """
        pending = []
        t0 = time.perf_counter()
        try:
            yield pending.append
        finally:
            for tree in pending:
                jax.block_until_ready(tree)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def record(self, name: str, seconds: float):
        self.totals[name] += seconds
        self.counts[name] += 1

    def summary(self) -> dict:
        return {
            name: {"total_s": round(self.totals[name], 6),
                   "count": self.counts[name],
                   "mean_s": round(self.totals[name]
                                   / max(self.counts[name], 1), 6)}
            for name in sorted(self.totals)
        }

    def reset(self):
        self.totals.clear()
        self.counts.clear()


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA profiler trace (view with TensorBoard/XProf)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Host-side phase marker visible on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)
