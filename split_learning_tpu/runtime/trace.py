"""Runtime tracing: XLA profiler capture + per-phase step timing.

The reference's only observability is tqdm bars, per-batch loss prints,
and a one-shot message-size probe (SURVEY.md §5.1); its in-message
``trace`` field is routing state, not tracing.  Here:

* :class:`StepTimer` — named wall-clock phase accumulators with
  ``jax.block_until_ready`` fencing, dumped as a metrics dict (feeds the
  metrics.jsonl sidecar, ``runtime/log.py``);
* :func:`trace` — context manager around ``jax.profiler`` writing a
  TensorBoard-loadable XLA trace;
* :func:`annotate` — ``TraceAnnotation`` wrapper so host-side round
  phases (plan/train/aggregate/validate) show up on the trace timeline;
* :class:`FaultCounters` — thread-safe failure/recovery counters
  (``drops``, ``timeouts``, ``redeliveries``, ``dedup_hits``,
  ``reconnects``, ...) shared by the transport stack
  (``runtime/bus.py`` reliability layer, ``runtime/chaos.py`` fault
  injection, TCP reconnect) and surfaced by the protocol server into
  ``metrics.jsonl`` and its end-of-round log line, so chaos runs are
  observable instead of silently self-healing;
* :class:`LatencyHistogram` / :class:`HistogramSet` — fixed-bucket
  (log-spaced) latency histograms for frame RTT, broker queue wait,
  step time and encode/decode, surfaced as ``kind: latency``
  metrics.jsonl records next to the counters;
* :data:`FAULT_COUNTER_NAMES` / :data:`HISTOGRAM_NAMES` /
  :data:`GAUGE_NAMES` — the declared name registries the ``counters``
  slcheck analyzer holds every ``.inc``/``.observe``/``.set`` call
  site to (typo'd names silently mint dead keys otherwise).  The
  ``GaugeSet`` type the gauge registry covers lives in
  ``runtime/telemetry.py`` with the rest of the live telemetry plane.
"""

from __future__ import annotations

import bisect
import collections
import contextlib
import math
import threading
import time

import jax

from split_learning_tpu.runtime import blackbox

#: Declared registry of every FaultCounters name the runtime may
#: increment.  ``FaultCounters.inc`` with a string literal outside this
#: set is a typo that would silently mint a new key (and a dashboard
#: nobody reads) — the ``counters`` slcheck analyzer
#: (``analysis/counters.py``) enforces membership statically.
FAULT_COUNTER_NAMES = frozenset({
    # chaos injection (runtime/chaos.py)
    "drops", "duplicates", "reorders", "corruptions", "delays",
    "crashes", "late_drops",
    # reliable delivery (runtime/bus.py ReliableTransport)
    "redeliveries", "dedup_hits", "resequenced", "lost", "gave_up",
    "daemon_errors", "ack_send_failures", "corrupt_rejected",
    # transport plumbing
    "reconnects", "timeouts", "async_send_errors", "prefetch_errors",
    # live telemetry plane (runtime/telemetry.py): heartbeat publishes
    # that failed, duplicate/reordered heartbeats the fleet monitor
    # rejected as stale, and barrier waits cut short because every
    # missing client was health-state `lost`
    "heartbeat_errors", "stale_heartbeats", "fleet_lost_drops",
    # hierarchical digest roll-up (runtime/sketch.py +
    # observability.digest-interval): duplicate/reordered FleetDigest
    # frames the server's seq guard rejected, and clients re-pointed
    # to direct heartbeats because their digest node died (one inc per
    # re-pointed client — the chaos cell's exact fallback count)
    "stale_digests", "digest_fallbacks",
    # wire codecs (runtime/codec/): non-finite payloads crossing the
    # quantizer, top-k leaves too small to sparsify, and the delta
    # codec's fold/full-frame/version-gap outcomes
    "quant_nonfinite", "topk_dense_fallbacks",
    "delta_folds", "delta_full_frames", "delta_resyncs",
    # performance-attribution plane (runtime/perf.py CompileWatch):
    # compiles observed after round 0 — the live twin of slcheck's
    # static retrace rule; rendered as sl_retraces_total on /metrics
    "retraces",
    # streaming aggregation plane (runtime/aggregate.py): duplicate
    # contributions the fold refused to double-weight, stale-generation
    # frames dropped at an L1 or the fallback drain, L1 aggregators
    # that died mid-round and degraded to the direct-to-root drain,
    # and group members abandoned because a dead L1 consumed their
    # UPDATE frames before dying (one inc per member)
    "agg_dup_drops", "agg_stale_drops", "agg_l1_fallbacks",
    "agg_fallback_abandons",
    # multi-process aggregator tree (runtime/aggnode.py): frames whose
    # ASSEMBLED chunked size broke the broker-cap twin in
    # FrameAssembler, partial frames a node/root could not decode
    # through the partial codec (missing/mismatched delta base), and
    # remote aggregator nodes declared dead (child exit or
    # FleetMonitor lost) whose groups fell back to the root drain
    "oversize_frames", "partial_codec_errors", "agg_node_deaths",
    # sync-mode round-boundary overlap (runtime/client.py
    # _sync_overlap_ticks): speculative caches the next START consumed
    # (spliced) vs invalidated-and-unwound (discarded)
    "overlap_splices", "overlap_discards",
    # async bounded-staleness admission window (runtime/server.py
    # _admit_update): contributions folded late with a decayed weight
    # (server_version - version <= learning.max-staleness), and
    # contributions past the window rejected and dropped
    "agg_stale_admits", "agg_stale_updates",
    # closed-loop scheduler (runtime/scheduler.py): clients evicted
    # through the elastic path, demoted with retuned knobs, adopted
    # cut re-plans, straggler clients a NOTIFY/UPDATE barrier dropped
    # mid-round after the scheduler grace, clients moved between
    # online clusters, and knob frames a client rejected (bad spec)
    "sched_evictions", "sched_demotions", "sched_replans",
    "sched_barrier_drops", "sched_cluster_moves",
    "sched_knob_rejects",
    # scheduler-driven aggregator fan-in retuning (ROADMAP item 1, 1M
    # tier): adopted aggregation.fan-in changes driven by measured
    # kind=agg_node fold walls
    "sched_fanin_retunes",
    # MPMD cross-host stage pipeline (runtime/stagehost.py +
    # pipeline.remote): stage hosts declared dead mid-round (child
    # exit or FleetMonitor lost), and later-stage client slots moved
    # to a surviving host (one inc per slot — the chaos cell's exact
    # fallback count), after which the invocation re-runs under a
    # fresh generation
    "stage_host_deaths", "stage_reassigns",
})

#: Declared registry of latency-histogram names (same contract as
#: FAULT_COUNTER_NAMES, enforced on ``.observe("name", ...)`` sites).
HISTOGRAM_NAMES = frozenset({
    "frame_rtt",       # publish wire-context t_send -> consume decode
    "queue_wait",      # broker enqueue -> dequeue (InProcTransport)
    "transport_rtt",   # reliable envelope t_send -> receiver pop
    "step",            # one hot-loop training step (bwd+apply / window)
    "encode",          # frame encode (device fetch + TENSOR framing)
    "decode",          # frame decode (assembler feed)
    # performance-attribution plane (runtime/perf.py StepTimer):
    # per-step dispatch wall (every step) and dispatch+device wall
    # (sampled steps only — the fenced ones)
    "step_dispatch", "step_device",
    # streaming aggregation plane (runtime/aggregate.py): wall of one
    # contribution's fold into the running sum (per Update / partial)
    "agg_fold",
})

#: Declared registry of gauge names (``runtime/telemetry.py GaugeSet``;
#: same contract as the two registries above, enforced on
#: ``.set("name", ...)`` sites by the ``counters`` analyzer CT003).
#: Unlike the counters/histograms, gauges are LAST-VALUE semantics:
#: each set overwrites, snapshots report the current value.
GAUGE_NAMES = frozenset({
    # client-side (set by the hot loops + heartbeat emitter)
    "round",           # current round index (set at SYN)
    "epoch",           # current local epoch within the round
    "inflight",        # stage-1 1F1B in-flight window depth
    "samples_per_s",   # EWMA training throughput (emitter tick)
    # performance-attribution plane (runtime/perf.py): model-FLOPs
    # utilization vs the datasheet peak, last sampled step's wall,
    # peak device bytes, cumulative compile wall, and samples/s over
    # device-busy time (distinguishes slow-compute from slow-wire)
    "mfu", "step_seconds", "hbm_peak_bytes", "compile_seconds_total",
    "compute_samples_per_s",
    # server-side (set by the FleetMonitor on every advance)
    "fleet_size", "fleet_healthy", "fleet_degraded",
    "fleet_straggler", "fleet_lost",
    # streaming aggregation plane (runtime/aggregate.py): host bytes
    # pinned by the delta codec's per-client shadow trees — the memory
    # the `lost`-client prune and elastic prune reclaim
    "agg_shadow_bytes",
    # standalone aggregator nodes (runtime/aggnode.py), set per round
    # and ridden on the node's heartbeats so /fleet and sl_top can
    # attribute a slow L1: contributions folded, wire bytes in/out of
    # the node's fold worker, and the round's fold wall
    "agg_node_folded", "agg_node_ingress_bytes",
    "agg_node_egress_bytes", "agg_node_fold_s", "agg_node_groups",
    # closed-loop scheduler (runtime/scheduler.py): wall milliseconds
    # of the last round-boundary decision pass (the control-plane cost
    # the 10k-client bench key pins flat), and the live online-cluster
    # count
    "sched_decision_ms", "sched_clusters",
    # hierarchical digest roll-up (runtime/sketch.py DIGEST_GAUGE_NAMES
    # — CT004 holds that registry to this one): nodes currently
    # reporting digests, clients covered by those digests, and the
    # server watchlist's size (the bounded exact-state population)
    "fleet_digest_nodes", "fleet_digest_clients", "fleet_watchlist",
    # sharded broker plane (runtime/bus.py Broker stats frames, polled
    # by the server's /fleet "brokers" sweep): shard processes
    # answering their stats control queue, and the plane-wide sums of
    # their connection counts, live queues, stored depth (+ high
    # water), parked GET continuations and wire bytes
    "broker_shards_up", "broker_conns", "broker_queues",
    "broker_depth", "broker_depth_hwm", "broker_parked_gets",
    "broker_bytes_in", "broker_bytes_out",
    # MPMD stage pipeline (runtime/client.py later-stage hot loops +
    # runtime/stagehost.py): a later-stage client's local ingest
    # backlog (buffered SDA window batches at the head, awaiting-
    # gradient in-flight entries at a middle stage), and the slot
    # count a stage host is currently running — both ride heartbeats
    # so sl_top can name a backed-up hop
    "queue_depth", "stage_slots",
    # flight recorder (runtime/blackbox.py): ring depth and seconds
    # since the participant's last dump, ridden on heartbeats so
    # /fleet and sl_top's BLACKBOX column can show per-participant
    # capture state (-1 age = never dumped)
    "blackbox_ring_depth", "blackbox_last_dump_age_s",
})


class FaultCounters:
    """Monotonic named counters; values never reset during a run, so
    consumers diff successive snapshots (same contract as the server's
    cumulative wire-byte metrics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: collections.Counter = collections.Counter()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] += n
        # flight-recorder feed (runtime/blackbox.py): every counter
        # increment is a "something abnormal was absorbed" event — the
        # per-process ring keeps the last N with timestamps, which is
        # the ordering the monotonic totals erase
        if blackbox.enabled():
            blackbox.record("fault", name=name, n=n)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())


#: process-wide default registry: every transport wrapper created without
#: an explicit ``faults=`` lands here, so one process's server sees its
#: clients' counters too in single-process (inproc) deployments
default_fault_counters = FaultCounters()


class WireCounters:
    """Thread-safe wire-traffic counters (same monotonic contract as
    :class:`FaultCounters`: values never reset, consumers diff
    successive snapshots): bytes sent/received per queue, cumulative
    encode/decode seconds, and the async sender-queue high-water mark.
    Fed by the transport stack (``runtime/bus.py AsyncTransport``) and
    the protocol codec call sites; surfaced into ``metrics.jsonl`` by
    the server's end-of-round summary and each client's round-end
    record."""

    #: queue-name prefixes classified as data-plane traffic
    _DATA_PREFIXES = ("intermediate_queue", "gradient_queue")

    def __init__(self):
        self._lock = threading.Lock()
        self._bytes_out: collections.Counter = collections.Counter()
        self._bytes_in: collections.Counter = collections.Counter()
        self._raw_bytes_out: collections.Counter = collections.Counter()
        self._msgs_out = 0
        self._msgs_in = 0
        self._encode_s = 0.0
        self._encode_n = 0
        self._decode_s = 0.0
        self._decode_n = 0
        self._send_queue_hwm = 0

    def count_out(self, queue: str, nbytes: int) -> None:
        with self._lock:
            self._bytes_out[queue] += nbytes
            self._msgs_out += 1

    def count_raw(self, queue: str, nbytes: int) -> None:
        """Pre-codec dense-equivalent bytes of a payload published on
        ``queue`` (what the plain wire-dtype path would have moved):
        the denominator of the wire compression ratio.  Only codec
        paths count here, so zero means no codec was active."""
        with self._lock:
            self._raw_bytes_out[queue] += nbytes

    def count_in(self, queue: str, nbytes: int) -> None:
        with self._lock:
            self._bytes_in[queue] += nbytes
            self._msgs_in += 1

    def add_encode(self, seconds: float) -> None:
        with self._lock:
            self._encode_s += seconds
            self._encode_n += 1

    def add_decode(self, seconds: float) -> None:
        with self._lock:
            self._decode_s += seconds
            self._decode_n += 1

    def note_send_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self._send_queue_hwm:
                self._send_queue_hwm = depth

    def per_queue(self) -> dict:
        with self._lock:
            return {"bytes_out": dict(self._bytes_out),
                    "bytes_in": dict(self._bytes_in)}

    def _data_bytes(self, counter) -> int:
        return sum(n for q, n in counter.items()
                   if q.startswith(self._DATA_PREFIXES))

    def snapshot(self) -> dict:
        """Flat record for metrics.jsonl (zero-valued fields included —
        callers prune)."""
        with self._lock:
            return {
                "bytes_out_total": sum(self._bytes_out.values()),
                "bytes_in_total": sum(self._bytes_in.values()),
                "raw_bytes_out": sum(self._raw_bytes_out.values()),
                "data_bytes_out": self._data_bytes(self._bytes_out),
                "data_bytes_in": self._data_bytes(self._bytes_in),
                "data_raw_bytes_out": self._data_bytes(
                    self._raw_bytes_out),
                "msgs_out": self._msgs_out,
                "msgs_in": self._msgs_in,
                "encode_s": round(self._encode_s, 6),
                "encode_n": self._encode_n,
                "decode_s": round(self._decode_s, 6),
                "decode_n": self._decode_n,
                "send_queue_hwm": self._send_queue_hwm,
            }


#: process-wide default, mirroring ``default_fault_counters``
default_wire_counters = WireCounters()


class LatencyHistogram:
    """Fixed-bucket latency histogram: log-spaced bounds from 1 µs to
    ~64 s (factor 2^0.25 per bucket, so a reported percentile is within
    ~19% of the true value), O(log buckets) per observe, thread-safe.
    Monotonic like the counters above: never reset, consumers diff
    successive snapshots."""

    #: geometric bucket upper bounds (seconds); one overflow bucket past
    #: the last bound
    BOUNDS = tuple(1e-6 * (2 ** (i / 4)) for i in range(104))

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.BOUNDS) + 1)
        self._n = 0
        self._sum = 0.0
        self._max = 0.0

    def observe(self, seconds: float) -> None:
        if not (seconds >= 0.0):     # NaN/negative: clock went backward
            seconds = 0.0
        i = bisect.bisect_left(self.BOUNDS, seconds)
        with self._lock:
            self._counts[i] += 1
            self._n += 1
            self._sum += seconds
            if seconds > self._max:
                self._max = seconds

    def _bucket_value(self, i: int) -> float:
        """Representative value: geometric mean of the bucket's edges."""
        hi = self.BOUNDS[min(i, len(self.BOUNDS) - 1)]
        lo = self.BOUNDS[i - 1] if i > 0 else hi / (2 ** 0.25)
        return math.sqrt(lo * hi)

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]) in seconds."""
        with self._lock:
            n = self._n
            if n == 0:
                return 0.0
            rank = max(1, math.ceil(n * q / 100.0))
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= rank:
                    return min(self._bucket_value(i), self._max)
            return self._max

    def snapshot(self) -> dict:
        with self._lock:
            n = self._n
            if n == 0:
                return {}
            mean = self._sum / n
        return {"count": n, "mean_ms": round(mean * 1e3, 4),
                "p50_ms": round(self.percentile(50) * 1e3, 4),
                "p95_ms": round(self.percentile(95) * 1e3, 4),
                "p99_ms": round(self.percentile(99) * 1e3, 4),
                "max_ms": round(self._max * 1e3, 4)}


class HistogramSet:
    """Named latency histograms, created on first observe.  Names must
    come from :data:`HISTOGRAM_NAMES` (statically enforced by the
    ``counters`` analyzer); snapshots flow into metrics.jsonl as
    ``kind: latency`` records next to the counter records."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[str, LatencyHistogram] = {}

    def hist(self, name: str) -> LatencyHistogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LatencyHistogram()
            return h

    def observe(self, name: str, seconds: float) -> None:
        self.hist(name).observe(seconds)

    def snapshot(self) -> dict:
        with self._lock:
            hists = list(self._hists.items())
        return {name: snap for name, h in hists
                if (snap := h.snapshot())}


#: process-wide default: layers with no per-participant registry in
#: reach (the in-process broker's queue-wait clock, the reliable
#: receiver's envelope RTT) observe here, mirroring
#: ``default_fault_counters``
default_histograms = HistogramSet()


class StepTimer:
    """Accumulates wall-clock per named phase; device-fenced."""

    def __init__(self):
        self.totals: dict = collections.defaultdict(float)
        self.counts: dict = collections.defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time a phase.  The context yields a ``fence`` callable: pass
        it the pytree produced INSIDE the block and it is blocked on
        before the clock stops, so async dispatch doesn't hide device
        time::

            with timer.phase("step") as fence:
                out = step(...)
                fence(out)
        """
        pending = []
        t0 = time.perf_counter()
        try:
            yield pending.append
        finally:
            for tree in pending:
                jax.block_until_ready(tree)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def record(self, name: str, seconds: float):
        self.totals[name] += seconds
        self.counts[name] += 1

    def summary(self) -> dict:
        return {
            name: {"total_s": round(self.totals[name], 6),
                   "count": self.counts[name],
                   "mean_s": round(self.totals[name]
                                   / max(self.counts[name], 1), 6)}
            for name in sorted(self.totals)
        }

    def reset(self):
        self.totals.clear()
        self.counts.clear()


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA profiler trace (view with TensorBoard/XProf)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Host-side phase marker visible on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)
