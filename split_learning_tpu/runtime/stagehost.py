"""Standalone MPMD stage-host process (``pipeline.remote``).

The pipeline's later stages (the consumers of
``intermediate_queue_*`` activations, producers of
``gradient_queue_*`` cotangents) have so far lived in the same
process group the deployment harness started — the data-plane half of
multi-host was the last structural gap after PR 12 moved the
*aggregation* tree out of process and PR 15 sharded the broker.
Following the MPMD pipeline-parallelism blueprint (each stage its own
program on its own host, activations streamed over the network), this
module promotes later-stage clients to **standalone stage-host
processes** connected over the existing (sharded) TCP broker
(``tools/sl_stagehost.py`` / ``python -m split_learning_tpu.stagehost``):

* the host builds its transport with
  :func:`~split_learning_tpu.runtime.chaos.make_runtime_transport` and
  announces itself with a
  :class:`~split_learning_tpu.runtime.protocol.StageHello` on the rpc
  queue (re-sent until adopted), then heartbeats like any client
  (``kind="stage_host"``) — liveness is the HEARTBEAT/FleetMonitor
  plane, and a host the monitor marks ``lost`` (or whose spawned
  process exits) triggers the server's counted slot re-assignment,
  not a barrier stall;
* the server replies with a
  :class:`~split_learning_tpu.runtime.protocol.StageAssign` naming the
  later-stage client slots this host runs.  Each slot spins one inner
  :class:`~split_learning_tpu.runtime.client.ProtocolClient` thread
  under the ASSIGNED ``client_id`` — the inner client REGISTERs and
  then speaks the ordinary choreography, so the Reliable/Chaos/Async/
  codec transport stack, the generation fences and the PR 10 async
  plane (aux heads + bounded staleness, which absorbs inter-host
  jitter) all compose unchanged;
* a MID-ROUND re-assignment (another host died) arrives as a further
  StageAssign: the dead host's slots are adopted under the SAME
  client ids, so the per-client ShardRunner seed — and therefore the
  re-run round's fold — is bit-identical to the fault-free twin;
* the host's own heartbeats carry the per-hop view ``sl_top`` renders
  as ROLE=stage rows: slot count, summed samples/s EWMA, the inner
  hot loops' step histogram (teed into the host's set, so step p95
  rides the host beat) and the summed ingest backlog
  (``queue_depth``).  The inner clients additionally emit their own
  ``kind=perf`` records per round, which ``sl_perf`` merges into the
  per-hop compute|wire|wait attribution table.
"""

from __future__ import annotations

import argparse
import threading
import time

from split_learning_tpu.config import Config, from_yaml
from split_learning_tpu.runtime import blackbox
from split_learning_tpu.runtime.log import Logger
from split_learning_tpu.runtime.protocol import (
    BlackboxDump, FrameAssembler, Heartbeat, StageAssign, StageHello,
    Stop, encode, reply_queue, RPC_QUEUE,
)

#: seconds between StageHello re-sends while not yet adopted (the
#: server's startup purge may race a fast host's first hello — the
#: same re-REGISTER discipline clients use)
HELLO_RESEND_S = 2.0


class _TeeHists:
    """Forwards histogram observations to two sets: the inner client's
    own (its heartbeats keep their per-client step digests) and the
    host's (so the HOST beat carries a merged step histogram across
    its slots — the ``sl_top`` stage row's step p95)."""

    def __init__(self, own, host):
        self._own = own
        self._host = host

    def observe(self, name: str, value: float) -> None:
        self._own.observe(name, value)
        self._host.observe(name, value)

    def __getattr__(self, attr):
        # digests/snapshots read the inner client's own set
        return getattr(self._own, attr)


class SlotWorker(threading.Thread):
    """One assigned later-stage client slot: an ordinary
    :class:`ProtocolClient` under the assigned ``client_id``, on its
    own transport stack, driven to completion on this thread."""

    def __init__(self, host: "StageHost", slot: dict):
        cid = slot["client_id"]
        super().__init__(daemon=True, name=f"{host.host_id}-{cid}")
        self.host = host
        self.slot = dict(slot)
        self.client_id = cid
        self.client = host._make_client(self.slot)
        # tee the hot loop's step observations into the host's set
        self.client.hists = _TeeHists(self.client.hists, host.hists)

    def run(self) -> None:
        t0 = time.time()
        ok = True
        try:
            self.client.run()
        except Exception as e:  # noqa: BLE001 — a dead transport or a
            # fault unwinding the slot's hot loop means this slot is
            # done; the server's liveness plane (the inner client's
            # heartbeats died with it) and re-run machinery recover
            ok = False
            self.host.log.warning(
                f"slot {self.client_id} died: {e}")
        self.host.tracer.record(
            "stage.slot", t0, time.time(), always=True,
            client=self.client_id, stage=int(self.slot.get("stage", 0)),
            ok=ok)


class StageHost:
    """The host process: adoption hello, heartbeats, assignment loop.

    ``transport`` defaults to a fresh ``make_runtime_transport`` stack;
    tests inject a shared in-proc bus (and usually a ``make_client``
    factory wiring the inner clients onto the same bus)."""

    def __init__(self, cfg: Config, host_id: str, transport=None,
                 make_client=None, logger: Logger | None = None):
        self.cfg = cfg
        self.host_id = host_id
        from split_learning_tpu.runtime.trace import (
            FaultCounters, HistogramSet,
        )
        self.faults = FaultCounters()
        self.hists = HistogramSet()
        self._owns_bus = transport is None
        if transport is None:
            from split_learning_tpu.runtime.chaos import (
                make_runtime_transport,
            )
            transport = make_runtime_transport(cfg, host_id,
                                               faults=self.faults)
        self.bus = transport
        self._make_client = make_client or self._default_client
        self.log = logger or Logger.for_run(cfg, host_id, console=False)
        # span-plane membership: adoption, each StageAssign apply and
        # each slot's whole lifetime journal into
        # spans-{host_id}.jsonl, so sl_trace's merged fleet timeline
        # covers the stage tier (the inner clients keep their own
        # journals — this is the HOST's view)
        from split_learning_tpu.runtime.spans import make_tracer
        self.tracer = make_tracer(cfg, host_id)
        self._t_hello: float | None = None
        self._asm = FrameAssembler(faults=self.faults)
        # NOT named _stop: see aggnode.DigestWorker — threading
        # internals shadow that name on some interpreter versions
        self._halt = threading.Event()
        self.adopted = threading.Event()
        self.workers: dict[str, SlotWorker] = {}
        from split_learning_tpu.runtime.telemetry import (
            GaugeSet, TelemetryEmitter,
        )
        self.gauges = GaugeSet()
        obs = getattr(cfg, "observability", None)
        interval = obs.heartbeat_interval if obs is not None else 0.0
        self.emitter = TelemetryEmitter(
            host_id, self._beat, interval=interval, faults=self.faults,
            hists=self.hists, gauges=self.gauges,
            samples_fn=self._total_samples, kind="stage_host")

    # -- inner clients -------------------------------------------------------

    def _default_client(self, slot: dict):
        from split_learning_tpu.runtime.client import ProtocolClient
        return ProtocolClient(self.cfg, slot["client_id"],
                              int(slot["stage"]),
                              cluster=slot.get("cluster"))

    def _total_samples(self) -> int:
        return sum(w.client.num_samples for w in self.workers.values())

    def _refresh_gauges(self) -> None:
        self.gauges.set("stage_slots", len(self.workers))
        depth = 0.0
        for w in self.workers.values():
            depth += w.client.gauges.get("queue_depth", 0.0) or 0.0
        self.gauges.set("queue_depth", depth)

    def _beat(self, snapshot: dict) -> None:
        self._refresh_gauges()
        snapshot["gauges"] = self.gauges.snapshot()
        # the host's stage view: the (lowest) stage its slots run —
        # display only; per-stage measured rates come from the inner
        # clients' own stage-tagged heartbeats
        stages = sorted({int(w.slot.get("stage", 0))
                         for w in self.workers.values()})
        if stages:
            snapshot["stage"] = stages[0]
        self.bus.publish(RPC_QUEUE, encode(Heartbeat(
            client_id=self.host_id, telemetry=snapshot)))

    def _apply_assign(self, msg: StageAssign) -> None:
        slots = msg.slots or []
        t0 = time.time()
        self.log.received(
            f"STAGEASSIGN gen={msg.gen} slots={len(slots)}")
        if not self.adopted.is_set() and self._t_hello is not None:
            # hello -> first assignment: the adoption handshake
            self.tracer.record("stage.adopt", self._t_hello, t0,
                               always=True, gen=msg.gen)
        self.adopted.set()
        for slot in slots:
            cid = slot["client_id"]
            old = self.workers.get(cid)
            if old is not None and old.is_alive():
                # idempotent re-send of a slot this host already runs
                continue
            try:
                worker = SlotWorker(self, slot)
            except Exception as e:  # noqa: BLE001 — a slot that cannot
                # build (bad stage index, dead transport) must not kill
                # the host's other slots; the server's liveness plane
                # notices the missing client
                self.log.warning(
                    f"slot {cid} failed to start: {e}")
                continue
            self.workers[cid] = worker
            worker.start()
        self._refresh_gauges()
        self.tracer.record("stage.assign", t0, time.time(),
                           always=True, gen=msg.gen, round=msg.round_idx,
                           slots=len(slots))
        self.tracer.flush()

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:
        self._hello()
        self.emitter.start()
        next_hello = time.monotonic() + HELLO_RESEND_S
        try:
            while not self._halt.is_set():
                raw = self.bus.get(reply_queue(self.host_id),
                                   timeout=0.25)
                if raw is None:
                    if not self.adopted.is_set() \
                            and time.monotonic() >= next_hello:
                        self._hello()
                        next_hello = time.monotonic() + HELLO_RESEND_S
                    continue
                try:
                    msg = self._asm.feed(raw)
                except Exception as e:  # noqa: BLE001 — one corrupt
                    # frame costs one message, not the host
                    self.faults.inc("corrupt_rejected")
                    self.log.warning(f"dropping undecodable frame: {e}")
                    continue
                if msg is None:
                    continue
                if isinstance(msg, Stop):
                    self.log.received(f"STOP ({msg.reason})")
                    break
                if isinstance(msg, BlackboxDump):
                    # server-initiated fleet snapshot: flush this
                    # host's flight recorder alongside everyone else's
                    blackbox.record("dump_request", reason=msg.reason)
                    blackbox.dump(msg.reason or "fleet_snapshot")
                    continue
                if isinstance(msg, StageAssign):
                    self._apply_assign(msg)
        finally:
            # the inner clients receive their own STOPs from the
            # server's fan-out (they are registrations like any
            # client's); give them a bounded drain
            for w in self.workers.values():
                w.join(timeout=10.0)
            self.emitter.stop()
            self.tracer.close()
            if self._owns_bus:
                try:
                    self.bus.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            self.log.close()

    def _hello(self) -> None:
        if self._t_hello is None:
            self._t_hello = time.time()
        self.bus.publish(RPC_QUEUE, encode(StageHello(
            host_id=self.host_id, capacity=len(self.workers))))
        self.log.sent("STAGEHELLO")


def write_host_config(cfg: Config, path) -> None:
    """Persist a config for spawned stage-host subprocesses (JSON is a
    YAML subset; ``from_yaml`` reads it back — same contract as
    ``aggnode.write_node_config``)."""
    import json

    from split_learning_tpu.config import to_dict
    with open(path, "w") as f:
        json.dump(to_dict(cfg), f, default=list)


def spawn_stage_host(config_path, host_id: str, cpu: int | None = None):
    """Spawn one stage-host subprocess (tcp transport).  ``cpu`` pins
    the child to one core via ``taskset``-free sched_setaffinity
    inheritance (the child re-pins itself from ``SLT_PIN_CPU``) — the
    bench's NUMA proxy.  JAX_PLATFORMS is pinned to cpu unless the
    caller set it; stdio is inherited so tracebacks surface in CI."""
    import os
    import subprocess
    import sys
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if cpu is not None:
        env["SLT_PIN_CPU"] = str(cpu)
    return subprocess.Popen(
        [sys.executable, "-m", "split_learning_tpu.stagehost",
         "--config", str(config_path), "--host-id", host_id], env=env)


def main(argv=None):
    import os
    ap = argparse.ArgumentParser(
        description="Standalone split-learning stage host "
                    "(pipeline.remote).")
    ap.add_argument("--config", default="config.yaml")
    ap.add_argument("--host-id", default="stage_host_0")
    args = ap.parse_args(argv)
    pin = os.environ.get("SLT_PIN_CPU")
    if pin is not None and hasattr(os, "sched_setaffinity"):
        try:
            os.sched_setaffinity(0, {int(pin)})
        except (OSError, ValueError):
            pass   # a bad pin must not stop the host from serving
    cfg = from_yaml(args.config)
    from split_learning_tpu.platform import apply_compile_cache
    apply_compile_cache(cfg.compile_cache_dir)
    blackbox.install(cfg, args.host_id, role="stage_host")
    host = StageHost(cfg, args.host_id)
    host.run()


if __name__ == "__main__":
    main()
