"""Server-side full-model validation.

Parity with ``/root/reference/src/val/get_val.py`` + ``src/val/VGG16.py:8-38``:
after aggregation the server reassembles the full model and runs the real
test set, logging loss/accuracy; a NaN or exploded loss marks the round
failed (``other/Vanilla_SL/src/Validation.py:55-59``), which the round loop
uses to skip checkpointing.

Here validation is one jitted eval step scanned over a static-shape test
loader — the same ``SplitModel`` with ``start_layer=0, end_layer=-1``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from split_learning_tpu.data import make_data_loader
from split_learning_tpu.models import build_model

_MODEL_DATASET = {
    # model registry key -> dataset provider name
    "VGG16_CIFAR10": "CIFAR10",
    "VGG16_CIFAR100": "CIFAR100",
    "VGG16_MNIST": "MNIST",
    "BERT_AGNEWS": "AGNEWS",
    "BERT_EMOTION": "EMOTION",
    "KWT_SPEECHCOMMANDS": "SPEECHCOMMANDS",
}


def dataset_for_model(model_key: str) -> str:
    if model_key in _MODEL_DATASET:
        return _MODEL_DATASET[model_key]
    # registry convention {MODEL}_{DATASET}
    return model_key.rsplit("_", 1)[-1]


# datasets whose providers accept a ``vocab`` kwarg (token data)
_TOKEN_DATASETS = {"TINYSTORIES", "AGNEWS", "EMOTION"}


def dataset_kwargs_for_model(model_key: str,
                             model_kwargs: dict | None) -> dict:
    """Dataset-provider kwargs implied by the model's build kwargs.

    A model with an overridden ``vocab_size`` must draw token ids inside
    its own embedding table: out-of-range ids NaN-fill in ``nn.Embed``
    (jnp.take fill mode), which surfaces as every round failing with
    "NaN detected".  Threading the vocab here makes tiny-model YAMLs
    valid end-to-end."""
    mk = model_kwargs or {}
    if (dataset_for_model(model_key) in _TOKEN_DATASETS
            and mk.get("vocab_size")):
        return {"vocab": int(mk["vocab_size"])}
    return {}


@dataclasses.dataclass
class ValResult:
    loss: float
    accuracy: float
    num_samples: int

    @property
    def ok(self) -> bool:
        """Round acceptance: reject NaN/exploded loss."""
        return bool(np.isfinite(self.loss) and abs(self.loss) < 1e5)


def make_eval_step(model, has_stats: bool):
    @jax.jit
    def step(variables, x, labels):
        logits = model.apply(variables, x, train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).sum()
        correct = jnp.sum(jnp.argmax(logits, axis=-1) == labels)
        return loss, correct
    return step


def evaluate(model_key: str, variables: dict, batch_size: int = 200,
             max_batches: int | None = None,
             model_kwargs: dict | None = None,
             synthetic_size: int | None = None) -> ValResult:
    """Full-model test-set evaluation; ``variables`` holds host or device
    pytrees for params (+ batch_stats)."""
    model = build_model(model_key, **(model_kwargs or {}))
    loader = make_data_loader(
        dataset_for_model(model_key), batch_size, train=False,
        synthetic_size=synthetic_size,
        dataset_kwargs=dataset_kwargs_for_model(model_key, model_kwargs))
    step = make_eval_step(model, "batch_stats" in variables)
    total_loss = 0.0
    total_correct = 0
    n = 0
    for i, (x, labels) in enumerate(loader):
        if max_batches is not None and i >= max_batches:
            break
        loss, correct = step(variables, jnp.asarray(x),
                             jnp.asarray(labels))
        total_loss += float(loss)
        total_correct += int(correct)
        n += int(np.asarray(labels).size)   # token-level for LM labels
    return ValResult(loss=total_loss / max(n, 1),
                     accuracy=total_correct / max(n, 1), num_samples=n)
