"""Message transports for the control/data plane.

The reference's transport is a RabbitMQ broker spoken via pika
(``/root/reference/src/Server.py:57-61``); clients *poll* with
``basic_get`` + 0.5 s sleeps (``src/RpcClient.py:37-41``).  Here the same
named-queue semantics live behind one small interface with two backends:

* :class:`InProcTransport` — thread-safe in-process queues.  The whole
  training cell (server + N clients) runs in one process; this is the
  TPU-native default (the data plane then usually bypasses the bus
  entirely via the compiled mesh pipeline).
* :class:`TcpTransport` + :class:`Broker` — a length-prefixed TCP
  broker giving true multi-process / multi-host parity with the
  reference's deployment shape, without an external Erlang dependency.
  The broker is a **selectors event loop**: one thread per shard
  whatever the connection count, blocked GETs parked as timer-backed
  continuations, buffered partial reads/writes with per-connection
  send-queue backpressure caps.
* :func:`shard_for` + :class:`ShardedTcpTransport` — the sharded
  broker plane (``broker.shards``): N independent shard processes on
  consecutive ports, a deterministic family-aware queue→shard map
  shared by every participant, lazy per-shard connections with
  per-shard reconnect/backoff isolation.  The fleet's aggregate broker
  bandwidth scales with the number of shards instead of serializing
  through one process's GIL.

Blocking ``get`` uses real waits (condition variables / socket blocking),
not the reference's sleep-polling.

Robustness layers (chaos-grade runtime):

* :class:`TcpTransport` auto-reconnects with capped exponential backoff
  when the broker restarts mid-run (``ConnectionError``/
  ``BrokenPipeError`` used to kill the process);
* :class:`ReliableTransport` upgrades matching queues from at-most-once
  to **at-least-once, in-order** delivery: every published frame carries
  a ``(sender_token, seq)`` envelope with its own checksum, receivers
  ack each frame and deduplicate + resequence per ``(queue, sender)``,
  and an unacked frame is redelivered with bounded backoff.  Under a
  :class:`~split_learning_tpu.runtime.chaos.ChaosTransport` injecting
  drops/duplicates/reordering/corruption, the application above sees the
  exact sent byte stream, in order.

The wire codecs (``runtime/codec/``: quantized activations, top-k
gradients, delta Updates) sit ABOVE this whole stack, inside the
payload build: a codec transforms the message's tensor tree before
``encode_parts`` produces frame bytes, so every layer here — async
sender thunks, reliable envelopes, chaos injection, chunking, crc —
moves codec-compressed bytes without knowing a codec exists.  That
layering is what makes the chaos soaks compose: redelivered frames
carry the SAME compressed bytes, so error-feedback state (advanced at
payload-build time, before any fault can fire) stays deterministic.
"""

from __future__ import annotations

import collections
import fnmatch
import heapq
import json
import os
import re
import selectors
import socket
import struct
import threading
import time
import uuid
import zlib
from typing import Iterable

from split_learning_tpu.analysis.locks import make_condition, make_lock
from split_learning_tpu.runtime import blackbox


class QueueClosed(Exception):
    pass


def _bb_frame(ev: str, queue: str, nbytes: int) -> None:
    """Flight-recorder feed (``runtime/blackbox.py``): one ring event
    per frame actually touching the wire, recorded at the CONCRETE
    transports (InProc/Tcp) so the wrapper layers never double-count.
    Broker self-telemetry polls are skipped — a periodic stats sweep
    must not flush real traffic out of the bounded ring."""
    if blackbox.enabled() and not queue.startswith("__broker__."):
        blackbox.record(ev, queue=queue, nbytes=nbytes)


class Transport:
    """Named-queue message transport (byte payloads).

    Concrete transports call :meth:`_count` from ``publish`` so tests
    and metrics can audit wire traffic (e.g. FLEX's no-upload rounds
    must move no weight bytes) via :attr:`bytes_out`.
    """

    def __init__(self):
        # own lock: one transport is shared by server + client threads
        self._count_lock = make_lock("transport.count")
        self.bytes_out: dict = {}

    def publish(self, queue: str, payload: bytes) -> None:
        raise NotImplementedError

    def _count(self, queue: str, payload: bytes) -> None:
        with self._count_lock:
            self.bytes_out[queue] = (self.bytes_out.get(queue, 0)
                                     + len(payload))

    def total_bytes_out(self) -> int:
        with self._count_lock:
            return sum(self.bytes_out.values())

    def bytes_out_snapshot(self) -> dict:
        """Consistent copy of the per-queue publish-byte counters."""
        with self._count_lock:
            return dict(self.bytes_out)

    def get(self, queue: str, timeout: float | None = None) -> bytes | None:
        """Pop one message; block up to ``timeout`` (None = forever).
        Returns None on timeout."""
        raise NotImplementedError

    def purge(self, queues: Iterable[str] | None = None) -> None:
        """Drop pending messages (all queues if None) — the reference's
        ``delete_old_queues`` hygiene (``src/Utils.py:8-32``)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcTransport(Transport):
    """Entries are ``(t_enqueue, payload)``: the dequeue observes the
    broker-level queue wait into the process-wide ``queue_wait``
    latency histogram (``runtime/trace.py``), the one hop the frame's
    own trace context cannot time from either endpoint."""

    def __init__(self):
        super().__init__()
        self._lock = make_lock("inproc")
        self._cond = make_condition("inproc", self._lock)
        self._queues: dict[str, collections.deque] = \
            collections.defaultdict(collections.deque)
        self._closed = False
        from split_learning_tpu.runtime.trace import default_histograms
        self._hists = default_histograms

    def publish(self, queue: str, payload: bytes) -> None:
        self._count(queue, payload)
        _bb_frame("publish", queue, len(payload))
        with self._cond:
            if self._closed:
                raise QueueClosed(queue)
            self._queues[queue].append((time.perf_counter(), payload))
            self._cond.notify_all()

    def get(self, queue: str, timeout: float | None = None) -> bytes | None:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._closed or self._queues[queue], timeout)
            if self._closed:
                raise QueueClosed(queue)
            if not ok:
                return None
            t_enq, payload = self._queues[queue].popleft()
        # histogram has its own lock: observe OUTSIDE the bus condition
        self._hists.observe("queue_wait", time.perf_counter() - t_enq)
        _bb_frame("consume", queue, len(payload))
        return payload

    def qsize(self, queue: str) -> int:
        with self._lock:
            return len(self._queues[queue])

    def purge(self, queues: Iterable[str] | None = None) -> None:
        with self._cond:
            if queues is None:
                self._queues.clear()
            else:
                for q in queues:
                    self._queues.pop(q, None)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# --------------------------------------------------------------------------
# TCP broker
# --------------------------------------------------------------------------
# Frame: 1-byte op | 4-byte BE queue-name len | name | 8-byte BE payload len
# | payload.  Ops: P=publish, G=get(blocking; payload = 8-byte BE timeout in
# ms, 0 = forever), X=purge, R=reply (broker->client; zero payload len and
# flag 0xFF means timeout).

_OP_PUB, _OP_GET, _OP_PURGE, _OP_REPLY = b"P", b"G", b"X", b"R"
_TIMEOUT_SENTINEL = 0xFFFFFFFFFFFFFFFF

#: frame sanity caps — a corrupt length prefix must fail the connection,
#: not drive the broker into a multi-terabyte allocation
MAX_NAME_BYTES = 1 << 16
MAX_FRAME_BYTES = 1 << 33          # 8 GiB; broker.py --max-frame-gb


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, op: bytes, name: bytes,
                payload: bytes) -> None:
    sock.sendall(op + struct.pack(">I", len(name)) + name
                 + struct.pack(">Q", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> tuple[bytes, bytes, bytes]:
    op = _recv_exact(sock, 1)
    (nlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    if nlen > MAX_NAME_BYTES:
        raise ConnectionError(f"corrupt frame: queue-name length {nlen}")
    name = _recv_exact(sock, nlen)
    (plen,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if plen == _TIMEOUT_SENTINEL:
        return op, name, None  # type: ignore[return-value]
    if plen > MAX_FRAME_BYTES:
        raise ConnectionError(f"corrupt frame: payload length {plen}")
    return op, name, _recv_exact(sock, plen)


#: control queue: a GET on this name returns the shard's stats frame
#: (JSON) immediately instead of popping a message — broker
#: self-telemetry without a new wire op, so every existing client
#: (and ``nc``-grade tooling) can scrape a shard
BROKER_STATS_QUEUE = "__broker__.stats"

#: control queue: a GET on this name returns the shard's flight-
#: recorder dump (JSON: header + ring events + shard stats) instead of
#: popping a message (``runtime/blackbox.py``).  The REQUESTER owns the
#: dump directory — the server's fleet-snapshot sweep writes the reply
#: to ``blackbox-broker-shard{i}.json`` next to the participants' own
#: dumps, so broker shards need no filesystem coordination.
BROKER_BLACKBOX_QUEUE = "__broker__.blackbox"

#: read chunk per readable event
_RECV_CHUNK = 1 << 18


class _ParkedGet:
    """One blocked GET continuation, parked on the event loop (a
    long-poll timer, not a blocked thread)."""

    __slots__ = ("conn", "queue", "deadline", "done")

    def __init__(self, conn: "_BrokerConn", queue: str,
                 deadline: float | None):
        self.conn = conn
        self.queue = queue
        self.deadline = deadline
        self.done = False


class _BrokerConn:
    """Per-connection state: incremental frame parser + buffered
    writer.  Never blocks the loop — partial reads accumulate in
    ``rbuf``, partial writes drain from ``wbuf`` on writable events."""

    __slots__ = ("sock", "rbuf", "wbuf", "woff", "wbytes", "paused",
                 "parked", "closed")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()
        self.wbuf: collections.deque = collections.deque()
        self.woff = 0          # bytes of wbuf[0] already sent
        self.wbytes = 0        # total bytes buffered
        self.paused = False    # read interest dropped (backpressure)
        self.parked: list[_ParkedGet] = []
        self.closed = False


class Broker:
    """Event-loop TCP message broker: ONE ``selectors``-driven thread
    per shard regardless of connection count (the thread-per-connection
    ancestor cost two threads per client, which is what capped the
    single broker process at a few thousand connections).

    * blocked GETs are parked continuations with a deadline on the
      loop's timer heap — a publish to the queue completes the oldest
      parked GET directly, a deadline sends the timeout reply;
    * reads and writes are non-blocking and buffered per connection; a
      connection whose outbound buffer exceeds :data:`SEND_QUEUE_CAP`
      stops being READ until it drains below the resume mark
      (backpressure instead of unbounded broker-side buffering);
    * the wire format, the ``MAX_FRAME_BYTES`` sanity cap and the
      same-port rebind-after-restart semantics are bit-compatible with
      the threaded broker, so :class:`TcpTransport`,
      :class:`ReliableTransport` and the chaos stack compose unchanged;
    * a GET on :data:`BROKER_STATS_QUEUE` answers immediately with the
      shard's JSON stats frame (conns, queues, depth high-water, bytes
      in/out, parked gets) — the self-telemetry ``sl_top`` renders as
      ROLE=broker rows.
    """

    #: outbound bytes buffered for one connection before the loop stops
    #: reading from it; resumes below the low-water mark.  Applies per
    #: connection, so one slow consumer cannot balloon the broker RSS
    #: while healthy peers stream on.
    SEND_QUEUE_CAP = 64 << 20
    SEND_QUEUE_RESUME = 8 << 20

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 bind_timeout: float = 10.0,
                 shard_id: str | None = None, tracer=None):
        # a RESTARTED broker re-binds the same port while the previous
        # incarnation's connections may still be draining (FIN_WAIT):
        # retry briefly instead of failing the recovery path
        deadline = time.monotonic() + bind_timeout
        while True:
            try:
                self._sock = socket.create_server((host, port))
                break
            except OSError:
                if port == 0 or time.monotonic() >= deadline:
                    raise
                time.sleep(0.1)
        self.host, self.port = self._sock.getsockname()[:2]
        self.shard_id = shard_id or f"broker@{self.host}:{self.port}"
        self._sock.setblocking(False)
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._sock, selectors.EVENT_READ, None)
        # wake pipe: close() (any thread) writes one byte so the loop
        # notices shutdown without waiting out its select timeout
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._queues: dict[str, collections.deque] = {}
        self._parked: dict[str, collections.deque] = {}
        self._timers: list = []     # heap of (deadline, seq, _ParkedGet)
        self._tseq = 0
        self._conns: dict[int, _BrokerConn] = {}
        self._t0 = time.monotonic()
        self._stats = collections.Counter()
        self._depth = 0             # total stored messages
        self._depth_hwm = 0
        self._running = True
        self._closed = threading.Event()
        # span plane (runtime/spans.py): shard loops journal coarse
        # "broker.tick" spans (depth/conns attrs) plus one span per
        # control-queue request, so tools/sl_trace.py merges the
        # broker shards onto the same fleet timeline as every other
        # participant.  None = no journal (in-process test brokers).
        self._tracer = tracer
        self._last_tick = time.time()
        from split_learning_tpu.runtime.trace import default_histograms
        self._hists = default_histograms
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"broker-{self.port}")
        self._thread.start()

    # -- event loop ----------------------------------------------------------

    def _loop(self) -> None:
        try:
            while self._running:
                timeout = 1.0
                if self._timers:
                    timeout = max(0.0, min(
                        timeout, self._timers[0][0] - time.monotonic()))
                for key, ready in self._sel.select(timeout):
                    if key.data is None:
                        self._accept()
                    elif key.data == "wake":
                        try:
                            self._wake_r.recv(64)
                        except OSError:
                            pass
                    else:
                        # the READY mask, not key.events (the
                        # registered interest): a read-ready wakeup
                        # must not burn a send syscall and vice versa
                        self._service(key.data, ready)
                self._fire_timers()
                self._tick_span()
        finally:
            if self._tracer is not None:
                try:
                    self._tracer.close()
                except Exception:  # slcheck: no-blackbox — teardown
                    pass
            self._teardown()

    def _tick_span(self) -> None:
        """Coarse shard-health span every ~2 s: cheap enough for the
        event loop, dense enough that a merged trace (and a blackbox
        dump's span feed) shows the shard alive with its depth/conns
        right up to the kill."""
        if self._tracer is None:
            return
        now = time.time()
        if now - self._last_tick < 2.0:
            return
        self._tracer.record("broker.tick", self._last_tick, now,
                            always=True, depth=self._depth,
                            conns=len(self._conns),
                            queues=len(self._queues))
        self._last_tick = now
        self._tracer.flush()

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._sock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            conn = _BrokerConn(sock)
            self._conns[sock.fileno()] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _interest(self, conn: _BrokerConn) -> None:
        events = 0
        if not conn.paused:
            events |= selectors.EVENT_READ
        if conn.wbuf:
            events |= selectors.EVENT_WRITE
        try:
            if events:
                self._sel.modify(conn.sock, events, conn)
            else:
                # nothing to do for this conn right now: stay
                # registered read-only so a peer close still surfaces
                self._sel.modify(conn.sock, selectors.EVENT_READ, conn)
                conn.paused = False
        except (KeyError, ValueError, OSError):
            pass

    def _service(self, conn: _BrokerConn, events: int) -> None:
        if conn.closed:
            return
        if events & selectors.EVENT_WRITE:
            self._flush(conn)
        if conn.closed or conn.paused:
            return
        if events & selectors.EVENT_READ:
            try:
                chunk = conn.sock.recv(_RECV_CHUNK)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._drop(conn)
                return
            if not chunk:
                self._drop(conn)
                return
            self._stats["bytes_in"] += len(chunk)
            conn.rbuf += chunk
            self._parse(conn)

    def _parse(self, conn: _BrokerConn) -> None:
        buf = conn.rbuf
        off = 0
        while not conn.closed:
            if len(buf) - off < 5:
                break
            op = buf[off:off + 1]
            (nlen,) = struct.unpack_from(">I", buf, off + 1)
            if nlen > MAX_NAME_BYTES:
                self._drop(conn)
                return
            if len(buf) - off < 5 + nlen + 8:
                break
            name = bytes(buf[off + 5:off + 5 + nlen])
            (plen,) = struct.unpack_from(">Q", buf, off + 5 + nlen)
            if plen > MAX_FRAME_BYTES:
                # corrupt length prefix: fail the connection, never
                # the multi-terabyte allocation
                self._drop(conn)
                return
            if len(buf) - off < 13 + nlen + plen:
                break
            payload = bytes(buf[off + 13 + nlen:off + 13 + nlen + plen])
            off += 13 + nlen + plen
            self._handle(conn, op, name, payload)
        if off:
            del conn.rbuf[:off]

    def _handle(self, conn: _BrokerConn, op: bytes, name: bytes,
                payload: bytes) -> None:
        try:
            queue = name.decode()
        except UnicodeDecodeError:
            self._drop(conn)
            return
        if op == _OP_PUB:
            self._stats["published"] += 1
            self._publish(queue, payload)
        elif op == _OP_GET:
            if len(payload) != 8:
                self._drop(conn)
                return
            (ms,) = struct.unpack(">Q", payload)
            self._get(conn, queue, ms)
        elif op == _OP_PURGE:
            self._stats["purges"] += 1
            self._purge(None if not payload
                        else payload.decode().split(","))
        else:
            self._drop(conn)

    # -- queue machinery -----------------------------------------------------

    def _publish(self, queue: str, payload: bytes) -> None:
        parked = self._parked.get(queue)
        while parked:
            pg = parked.popleft()
            if not parked:
                del self._parked[queue]
            if pg.done or pg.conn.closed:
                continue
            pg.done = True
            # a parked consumer waited zero broker-residency time;
            # observing 0 keeps the queue_wait histogram's population
            # covering EVERY delivery (the threaded broker's store
            # observed enqueue->dequeue for all of them), so the
            # percentiles don't bias toward the slow stored path
            self._hists.observe("queue_wait", 0.0)
            self._reply(pg.conn, payload)
            return
        q = self._queues.get(queue)
        if q is None:
            q = self._queues[queue] = collections.deque()
        q.append((time.perf_counter(), payload))
        self._depth += 1
        if self._depth > self._depth_hwm:
            self._depth_hwm = self._depth

    def _get(self, conn: _BrokerConn, queue: str, ms: int) -> None:
        if queue == BROKER_STATS_QUEUE:
            self._reply(conn, json.dumps(self.stats()).encode())
            return
        if queue == BROKER_BLACKBOX_QUEUE:
            # on-demand flight-recorder dump: serialized in-memory and
            # sent to the requester (who owns the dump directory);
            # the shard's stats ride along as a ring-independent floor
            # so even a blackbox-disabled shard answers usefully
            if self._tracer is not None:
                self._tracer.record("broker.blackbox", time.time(),
                                    time.time(), always=True)
            self._reply(conn, blackbox.dump_bytes(
                "request", extra={"stats": self.stats()},
                participant=blackbox.ring().participant
                or self.shard_id))
            return
        q = self._queues.get(queue)
        if q:
            t_enq, payload = q.popleft()
            if not q:
                del self._queues[queue]
            self._depth -= 1
            # histogram has its own lock; observing here is the same
            # broker-residency clock InProcTransport kept
            self._hists.observe("queue_wait",
                                time.perf_counter() - t_enq)
            self._reply(conn, payload)
            return
        deadline = (None if ms == 0
                    else time.monotonic() + ms / 1000.0)
        pg = _ParkedGet(conn, queue, deadline)
        self._parked.setdefault(queue, collections.deque()).append(pg)
        conn.parked.append(pg)
        if len(conn.parked) > 32:
            # the list exists so _drop can cancel a dying connection's
            # continuations; compact completed ones as we go or a
            # long-poll loop grows it one entry per GET forever
            conn.parked = [p for p in conn.parked if not p.done]
        if deadline is not None:
            self._tseq += 1
            heapq.heappush(self._timers, (deadline, self._tseq, pg))

    def _fire_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _, _, pg = heapq.heappop(self._timers)
            if pg.done or pg.conn.closed:
                continue
            pg.done = True
            self._stats["get_timeouts"] += 1
            self._enqueue(pg.conn, _OP_REPLY + struct.pack(">I", 0)
                          + struct.pack(">Q", _TIMEOUT_SENTINEL))
            # trim satisfied/expired heads so a poll-heavy queue's
            # parked deque cannot grow with dead continuations
            dq = self._parked.get(pg.queue)
            while dq and (dq[0].done or dq[0].conn.closed):
                dq.popleft()
            if dq is not None and not dq:
                self._parked.pop(pg.queue, None)

    def _purge(self, queues: list[str] | None) -> None:
        if queues is None:
            self._queues.clear()
            self._depth = 0
        else:
            for q in queues:
                gone = self._queues.pop(q, None)
                if gone:
                    self._depth -= len(gone)

    # -- buffered writes -----------------------------------------------------

    def _reply(self, conn: _BrokerConn, payload: bytes) -> None:
        self._stats["delivered"] += 1
        self._enqueue(conn, _OP_REPLY + struct.pack(">I", 0)
                      + struct.pack(">Q", len(payload)) + payload)

    def _enqueue(self, conn: _BrokerConn, frame: bytes) -> None:
        if conn.closed:
            return
        conn.wbuf.append(frame)
        conn.wbytes += len(frame)
        self._flush(conn)
        if conn.closed:
            return
        if conn.wbytes > self.SEND_QUEUE_CAP and not conn.paused:
            # backpressure: stop READING from a connection we cannot
            # drain — its GETs/publishes wait in ITS kernel buffers,
            # not in broker heap
            conn.paused = True
            self._stats["backpressure_pauses"] += 1
        self._interest(conn)

    def _flush(self, conn: _BrokerConn) -> None:
        try:
            while conn.wbuf:
                head = conn.wbuf[0]
                sent = conn.sock.send(
                    memoryview(head)[conn.woff:])
                if sent <= 0:
                    break
                self._stats["bytes_out"] += sent
                conn.woff += sent
                conn.wbytes -= sent
                if conn.woff >= len(head):
                    conn.wbuf.popleft()
                    conn.woff = 0
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._drop(conn)
            return
        if conn.paused and conn.wbytes < self.SEND_QUEUE_RESUME:
            conn.paused = False
        self._interest(conn)

    def _drop(self, conn: _BrokerConn) -> None:
        if conn.closed:
            return
        conn.closed = True
        for pg in conn.parked:
            pg.done = True
        conn.parked.clear()
        self._conns.pop(conn.sock.fileno(), None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- stats + lifecycle ---------------------------------------------------

    def stats(self) -> dict:
        """The shard's self-telemetry frame (also served on
        :data:`BROKER_STATS_QUEUE`).  Loop-thread state read without a
        lock: every field is a single int/str read, at worst one event
        stale — fine for telemetry."""
        parked = sum(sum(1 for pg in d if not pg.done)
                     for d in self._parked.values())
        return {
            "shard": self.shard_id, "host": self.host,
            "port": self.port, "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "conns": len(self._conns),
            "queues": len(self._queues),
            "depth": self._depth, "depth_hwm": self._depth_hwm,
            "parked_gets": parked,
            "threads": 1,
            "bytes_in": self._stats["bytes_in"],
            "bytes_out": self._stats["bytes_out"],
            "published": self._stats["published"],
            "delivered": self._stats["delivered"],
            "get_timeouts": self._stats["get_timeouts"],
            "purges": self._stats["purges"],
            "backpressure_pauses": self._stats["backpressure_pauses"],
        }

    def _teardown(self) -> None:
        # shutdown() BEFORE close(), listener and every connection: a
        # blocked client recv must see EOF, and the port must actually
        # release so a same-port broker RESTART (the recovery path
        # TcpTransport reconnects to) cannot hit EADDRINUSE forever
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in list(self._conns.values()):
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.sock.close()
            except OSError:
                pass
        self._conns.clear()
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        self._closed.set()

    def close(self):
        self._running = False
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=10.0)
        self._closed.wait(timeout=10.0)


# --------------------------------------------------------------------------
# queue sharding
# --------------------------------------------------------------------------

_DIGIT_RE = re.compile(r"\d+")


def shard_for(queue: str, shards: int) -> int:
    """Deterministic owner shard of ``queue`` among ``shards`` broker
    endpoints (ports ``base .. base+shards-1``).

    Stable across processes and restarts (crc32 + integer arithmetic,
    never :func:`hash`), and FAMILY-AWARE: the digits are lifted out of
    the name, the remaining family template is hashed once, and the
    instance indices are mixed back in — so ``intermediate_queue_0_0``,
    ``_0_1``, ``_0_2`` … round-robin across shards (consecutive indices
    hit consecutive shards) while any single queue always maps to
    exactly one shard.  Digit-free names (``rpc_queue``) hash on the
    family alone."""
    if shards <= 1:
        return 0
    family = _DIGIT_RE.sub("#", queue)
    h = zlib.crc32(family.encode())
    for d in _DIGIT_RE.findall(queue):
        # 1000003: odd prime ≫ any realistic shard count, so mixed
        # indices stay a bijection mod shards per digit group
        h = h * 1000003 + int(d)
    return h % shards


class ShardedTcpTransport(Transport):
    """Multi-endpoint :class:`TcpTransport`: one broker-shard plane.

    Routes every publish/get to the queue's owning shard
    (:func:`shard_for`), lazily opening one :class:`TcpTransport` per
    shard on first touch.  Each per-shard connection keeps its own
    socket, lock and reconnect/backoff state, so a dead shard stalls
    only operations on ITS queues — traffic to the surviving shards
    flows on, and the reliable layer above redelivers whatever the
    dead shard lost once it rebinds.  ``purge(None)`` broadcasts to
    every shard (the server's startup hygiene must sweep the whole
    plane)."""

    def __init__(self, host: str, port: int, shards: int,
                 connect_timeout: float = 30.0,
                 reconnect_timeout: float = 15.0, faults=None):
        super().__init__()
        self.host, self.port = host, int(port)
        self.shards = int(shards)
        self._connect_timeout = connect_timeout
        self._reconnect_timeout = reconnect_timeout
        self._faults = faults
        self._closed = False
        # guards the shard map only — connections are DIALED outside
        # it (a shard mid-backoff must not stall a sibling's lazy open)
        self._shard_lock = make_lock("tcp.shards")
        self._transports: dict[int, TcpTransport] = {}

    def shard_of(self, queue: str) -> int:
        return shard_for(queue, self.shards)

    def endpoint(self, shard: int) -> tuple[str, int]:
        return self.host, self.port + shard

    def _conn(self, shard: int) -> TcpTransport:
        t = self._transports.get(shard)
        if t is not None:
            return t
        if self._closed:
            raise ConnectionError("transport closed")
        host, port = self.endpoint(shard)
        fresh = TcpTransport(host, port,
                             connect_timeout=self._connect_timeout,
                             reconnect_timeout=self._reconnect_timeout,
                             faults=self._faults)
        with self._shard_lock:
            cur = self._transports.get(shard)
            if cur is None and not self._closed:
                self._transports[shard] = fresh
                return fresh
        fresh.close()   # lost the race (or closed under us)
        if cur is None:
            raise ConnectionError("transport closed")
        return cur

    def publish(self, queue: str, payload: bytes) -> None:
        self._count(queue, payload)
        self._conn(self.shard_of(queue)).publish(queue, payload)

    def get(self, queue: str, timeout: float | None = None
            ) -> bytes | None:
        return self._conn(self.shard_of(queue)).get(queue, timeout)

    def purge(self, queues: Iterable[str] | None = None) -> None:
        if queues is None:
            for shard in range(self.shards):
                self._conn(shard).purge(None)
            return
        by_shard: dict[int, list] = {}
        for q in queues:
            by_shard.setdefault(self.shard_of(q), []).append(q)
        for shard, qs in sorted(by_shard.items()):
            self._conn(shard).purge(qs)

    def close(self) -> None:
        with self._shard_lock:
            self._closed = True
            conns = list(self._transports.values())
            self._transports.clear()
        for t in conns:
            t.close()


def find_port_block(shards: int, host: str = "127.0.0.1",
                    lo: int = 20000, hi: int = 28000,
                    attempts: int = 64) -> int:
    """A base port with ``shards`` consecutive bindable ports — shard
    endpoints live at ``base .. base+shards-1``, and picking the block
    below the ephemeral range keeps client-socket collisions out of
    the plane.  Probe-and-release is inherently racy; callers that
    lose the race (bind failure at spawn) just call again."""
    import random
    rng = random.Random()
    for _ in range(attempts):
        base = rng.randrange(lo, hi)
        socks = []
        try:
            for i in range(shards):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind((host, base + i))
                socks.append(s)
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
        if len(socks) == shards:
            return base
    raise OSError(f"no free {shards}-port block in [{lo}, {hi})")


def broker_stats(host: str, port: int, timeout: float = 2.0) -> dict:
    """One shard's stats frame (see :data:`BROKER_STATS_QUEUE`)."""
    t = TcpTransport(host, port, connect_timeout=timeout,
                     reconnect_timeout=timeout)
    try:
        raw = t.get(BROKER_STATS_QUEUE, timeout=timeout)
        if raw is None:
            raise ConnectionError("stats request timed out")
        return json.loads(raw.decode())
    finally:
        t.close()


def broker_blackbox(host: str, port: int, timeout: float = 2.0) -> dict:
    """One shard's flight-recorder dump (see
    :data:`BROKER_BLACKBOX_QUEUE`); the caller writes it to its own
    dump directory."""
    t = TcpTransport(host, port, connect_timeout=timeout,
                     reconnect_timeout=timeout)
    try:
        raw = t.get(BROKER_BLACKBOX_QUEUE, timeout=timeout)
        if raw is None:
            raise ConnectionError("blackbox request timed out")
        return json.loads(raw.decode())
    finally:
        t.close()


def collect_broker_stats(host: str, port: int, shards: int,
                         timeout: float = 1.5) -> list[dict]:
    """Stats from every shard of a broker plane; unreachable shards
    yield ``{"shard_index": i, "port": p, "error": ...}`` rows instead
    of failing the sweep (sl_top must render a PARTIALLY dead plane)."""
    out = []
    for i in range(max(1, int(shards))):
        try:
            s = broker_stats(host, port + i, timeout=timeout)
            s["shard_index"] = i
        except Exception as e:  # noqa: BLE001 — down/refused/timeout
            s = {"shard_index": i, "port": port + i,
                 "error": f"{type(e).__name__}: {e}"}
            # the REQUESTER's ring is where a dead shard leaves its
            # trace (the shard itself can't): the postmortem reads
            # shard_dead events from the surviving server's dump
            blackbox.record("shard_dead", shard=i, port=port + i,
                            err=type(e).__name__)
        out.append(s)
    return out


class TcpTransport(Transport):
    """Client of a :class:`Broker`. One socket per transport instance;
    safe for one thread (create one per worker thread).

    A mid-run ``ConnectionError``/``BrokenPipeError`` (broker restart,
    transient network reset) no longer kills the process: every op
    reconnects with capped exponential backoff and retries, up to
    ``reconnect_timeout`` seconds per outage.  Messages queued inside a
    restarted broker are gone — layer :class:`ReliableTransport` on top
    when that loss matters."""

    def __init__(self, host: str, port: int, connect_timeout: float = 30.0,
                 reconnect_timeout: float = 15.0, faults=None):
        super().__init__()
        self.host, self.port = host, port
        self._reconnect_timeout = reconnect_timeout
        self._closed = False
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        # the broker may still be coming up (simultaneous launch): retry
        # with backoff instead of failing the whole client process
        self._sock = self._connect(connect_timeout)
        # serializes the single socket, not state: blocking I/O (and the
        # reconnect backoff sleep) under it is this lock's PURPOSE
        self._lock = make_lock("tcp.io")  # slcheck: io-lock

    def _connect(self, timeout: float) -> socket.socket:
        deadline = time.monotonic() + timeout
        delay = 0.1
        while True:
            if self._closed:
                raise ConnectionError("transport closed")
            try:
                sock = socket.create_connection((self.host, self.port),
                                                timeout=10.0)
                sock.settimeout(None)
                return sock
            except (ConnectionRefusedError, ConnectionResetError,
                    TimeoutError):
                # only not-up-yet errors; bad hostnames etc. fail fast
                if time.monotonic() >= deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 2.0)   # capped exponential backoff

    def _reconnect(self) -> None:
        """Steady-state reconnect: the broker died under us."""
        try:
            self._sock.close()
        except OSError:
            pass
        self.faults.inc("reconnects")
        self._sock = self._connect(self._reconnect_timeout)

    _MAX_OP_RETRIES = 5

    def _retry(self, op):
        """Run ``op`` (which uses ``self._sock``); on a connection-level
        failure reconnect and re-issue.  Caller holds ``self._lock``.

        Bounded per op: when reconnects SUCCEED but the op keeps
        failing (e.g. the broker enforces a lower frame cap and kills
        the connection on every resend), retrying forever would be a
        hot connect/send/reset livelock — after ``_MAX_OP_RETRIES``
        consecutive failures the error surfaces to the caller.  A
        broker OUTAGE is bounded separately by ``reconnect_timeout``
        inside ``_reconnect``."""
        attempts = 0
        while True:
            try:
                return op()
            except (ConnectionError, OSError):
                # includes BrokenPipeError/ConnectionResetError; a close()
                # from our own side must still raise out to the caller
                if self._closed:
                    raise
                attempts += 1
                if attempts > self._MAX_OP_RETRIES:
                    raise
                self._reconnect()

    def publish(self, queue: str, payload: bytes) -> None:
        # fail fast on a frame the broker deterministically rejects:
        # _retry cannot tell a cap rejection from a transient outage and
        # would reconnect-and-resend the same doomed frame forever
        if len(payload) > MAX_FRAME_BYTES:
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds the "
                f"{MAX_FRAME_BYTES}-byte frame cap")
        self._count(queue, payload)
        _bb_frame("publish", queue, len(payload))
        with self._lock:
            self._retry(lambda: _send_frame(self._sock, _OP_PUB,
                                            queue.encode(), payload))

    def get(self, queue: str, timeout: float | None = None) -> bytes | None:
        ms = 0 if timeout is None else max(1, int(timeout * 1000))

        def once():
            _send_frame(self._sock, _OP_GET, queue.encode(),
                        struct.pack(">Q", ms))
            op, _, payload = _recv_frame(self._sock)
            if op != _OP_REPLY:
                raise ConnectionError(f"unexpected broker reply op {op!r}")
            return payload  # None on timeout

        with self._lock:
            # a reconnect mid-get re-issues the request: the original
            # GET (and any reply in flight) died with the old socket
            payload = self._retry(once)
        if payload is not None:
            _bb_frame("consume", queue, len(payload))
        return payload

    def purge(self, queues: Iterable[str] | None = None) -> None:
        payload = b"" if queues is None else ",".join(queues).encode()
        with self._lock:
            self._retry(lambda: _send_frame(self._sock, _OP_PURGE, b"",
                                            payload))

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


# --------------------------------------------------------------------------
# at-least-once, in-order delivery
# --------------------------------------------------------------------------
# Envelope: RB1 | crc32(body) | body, with
#   body(data) = 0x03 | 8B seq | 2B name-len | sender-token | f64 t-send
#                | payload
#   body(ack)  = 0x02 | 8B seq | 2B name-len | queue-name
# (kind 0x01 is the pre-timestamp data envelope: still ACCEPTED so
# this receiver can consume an old sender's frames, but an OLD
# receiver rejects 0x03 as corrupt — sender and receiver must upgrade
# together, like every other change to this envelope).  Data frames
# ride the application queue; acks
# ride ``__ack__.{token}``.  The envelope checksum is the first
# integrity line: a corrupt frame is silently discarded (no ack), so
# the sender's redelivery repairs it.  ``t-send`` is stamped once at
# first publish and survives redelivery, so the receiver's
# ``transport_rtt`` histogram measures the TRUE transport latency of
# each frame — redelivery delays included.

_ENV_MAGIC = b"RB1"
_ENV_DATA, _ENV_ACK, _ENV_DATA_TS = 0x01, 0x02, 0x03
_ENV_HDR = len(_ENV_MAGIC) + 4


def _env_frame(kind: int, seq: int, name: bytes, payload: bytes,
               t_send: float | None = None) -> bytes:
    body = struct.pack(">BQH", kind, seq, len(name)) + name
    if kind == _ENV_DATA_TS:
        body += struct.pack(">d", time.time() if t_send is None
                            else t_send)
    body += payload
    return _ENV_MAGIC + struct.pack(">I", zlib.crc32(body)) + body


def _env_parse(raw: bytes):
    """None = not an envelope; "corrupt" = failed integrity; else
    ``(kind, name, seq, payload, t_send)`` with kind normalized to
    ``_ENV_DATA``/``_ENV_ACK`` (t_send None when the frame has none)."""
    if not raw.startswith(_ENV_MAGIC):
        return None
    if len(raw) < _ENV_HDR + 11:
        return "corrupt"
    (want,) = struct.unpack_from(">I", raw, len(_ENV_MAGIC))
    body = raw[_ENV_HDR:]
    if zlib.crc32(body) != want:
        return "corrupt"
    kind, seq, nlen = struct.unpack_from(">BQH", body, 0)
    if kind not in (_ENV_DATA, _ENV_ACK, _ENV_DATA_TS) \
            or len(body) < 11 + nlen:
        return "corrupt"
    name = body[11:11 + nlen].decode("utf-8", "replace")
    t_send = None
    off = 11 + nlen
    if kind == _ENV_DATA_TS:
        if len(body) < off + 8:
            return "corrupt"
        (t_send,) = struct.unpack_from(">d", body, off)
        off += 8
        kind = _ENV_DATA
    return kind, name, seq, body[off:], t_send


def _ack_queue(token: str) -> str:
    return f"__ack__.{token}"


class ReliableTransport(Transport):
    """At-least-once, in-order delivery over any :class:`Transport`.

    Sender side (queues matching ``patterns``): each payload is wrapped
    in a sequence-numbered envelope, kept until acked, and redelivered
    with capped exponential backoff up to ``max_redeliver`` times
    (then counted ``gave_up`` and dropped — bounded redelivery, no
    infinite queues).  Receiver side (any queue — envelopes are
    self-describing): frames are acked on receipt, deduplicated on
    ``(queue, sender_token, seq)`` and resequenced back into the
    sender's publish order, so the layer above sees exactly-once
    in-order bytes as long as the sender keeps redelivering.  A gap
    whose frame was given up on is skipped after ``gap_timeout_s``
    (counted ``lost``), trading completeness for liveness.

    The sender token carries a per-instance nonce: a crashed-and-
    restarted participant starts a fresh sequence space instead of
    colliding with its predecessor's.

    The redelivery/ack daemon uses ``side`` when given (a second
    connection — required over :class:`TcpTransport`, whose blocking
    ``get`` serializes the socket) and ``inner`` otherwise (fine for
    :class:`InProcTransport`).  Non-matching queues pass through
    untouched, so control and data planes can mix policies on one bus.

    The default ``patterns`` come from ``TransportConfig.reliable_queues``
    (single source of truth), so directly-constructed instances and
    config-driven stacks can't silently diverge.
    """

    def __init__(self, inner: Transport, sender: str,
                 patterns: Iterable[str] | None = None,
                 side: Transport | None = None,
                 redeliver_s: float = 0.3, max_redeliver: int = 20,
                 gap_timeout_s: float | None = None, faults=None):
        super().__init__()
        self.inner = inner
        self._side = side if side is not None else inner
        self._own_side = side is not None
        self.sender = sender
        self.token = f"{sender}#{uuid.uuid4().hex[:8]}"
        if patterns is None:
            from split_learning_tpu.config import TransportConfig
            patterns = TransportConfig().reliable_queues
        self.patterns = tuple(patterns)
        self._redeliver_s = redeliver_s
        self._max_redeliver = max_redeliver
        if gap_timeout_s is None:
            # must exceed the sender's full retry horizon, whatever the
            # configured attempt count: a gap skipped while the sender
            # is still redelivering turns a late arrival into a
            # permanent loss (the skip moved `expected` past it)
            horizon = sum(min(redeliver_s * (1.5 ** k), 1.0)
                          for k in range(1, max_redeliver + 1))
            gap_timeout_s = horizon + 10.0
        self._gap_timeout_s = gap_timeout_s
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        from split_learning_tpu.runtime.trace import default_histograms
        self._hists = default_histograms
        self._lock = make_lock("reliable")
        self._seq: dict[str, int] = {}
        # (queue, seq) -> [frame, next_due, attempts]
        self._unacked: dict[tuple, list] = {}
        # receive state, guarded by _lock (get may be called from one
        # thread while purge/close run from another)
        # _expected must NEVER be pruned for a token that might still
        # send (forgetting the watermark would mis-hold its next frame
        # behind a phantom 0..N gap and count phantom losses); it is one
        # int per (queue, sender-incarnation) — the same order of growth
        # as the broker's queue map itself.  _held IS pruned when empty
        # so the per-get scans stay proportional to active gaps.
        self._expected: dict[tuple, int] = {}
        self._held: dict[tuple, dict] = {}
        self._gap_since: dict[tuple, float] = {}
        self._closed = threading.Event()
        self._daemon = threading.Thread(target=self._daemon_loop,
                                        daemon=True,
                                        name=f"reliable-{sender}")
        self._daemon.start()

    # -- sender ------------------------------------------------------------

    def _match(self, queue: str) -> bool:
        return any(fnmatch.fnmatchcase(queue, p) for p in self.patterns)

    def publish(self, queue: str, payload: bytes) -> None:
        if not self._match(queue):
            self.inner.publish(queue, payload)
            return
        with self._lock:
            seq = self._seq.get(queue, 0)
            self._seq[queue] = seq + 1
            frame = _env_frame(_ENV_DATA_TS, seq, self.token.encode(),
                               payload)
            self._unacked[(queue, seq)] = [
                frame, time.monotonic() + self._redeliver_s, 0]
        self.inner.publish(queue, frame)

    def _daemon_loop(self) -> None:
        """Consume acks; redeliver overdue unacked frames.

        The daemon must outlive ANY single failure: it is the only
        thread repairing losses, so an unexpected exception (a chaos
        wrapper below it, a frame-cap ValueError, a decoding surprise)
        is counted and survived — only shutdown and a closed bus end
        the loop."""
        ackq = _ack_queue(self.token)
        while not self._closed.is_set():
            try:
                raw = self._side.get(ackq, timeout=0.05)
            except QueueClosed:
                return
            except (ConnectionError, OSError):
                if self._closed.is_set():
                    return
                time.sleep(0.2)
                continue
            except Exception:  # noqa: BLE001 — see docstring
                self.faults.inc("daemon_errors")
                time.sleep(0.2)
                continue
            if raw is not None:
                parsed = _env_parse(raw)
                if (isinstance(parsed, tuple)
                        and parsed[0] == _ENV_ACK):
                    _, queue, seq, _, _ = parsed
                    with self._lock:
                        self._unacked.pop((queue, seq), None)
                continue   # drain the ack queue dry before redelivering
            now = time.monotonic()
            due = []
            with self._lock:
                for key, ent in list(self._unacked.items()):
                    if ent[1] > now:
                        continue
                    ent[2] += 1
                    if ent[2] > self._max_redeliver:
                        del self._unacked[key]
                        self.faults.inc("gave_up")
                        continue
                    # capped backoff: cheap early retries beat a long
                    # horizon — under sustained loss p the give-up odds
                    # are p^(attempts+1), so attempts are the lever
                    ent[1] = now + min(
                        self._redeliver_s * (1.5 ** ent[2]), 1.0)
                    due.append((key[0], ent[0]))
            for queue, frame in due:
                try:
                    self._side.publish(queue, frame)
                    self.faults.inc("redeliveries")
                except QueueClosed:
                    return
                except (ConnectionError, OSError):
                    break   # broker down: next tick retries
                except Exception:  # noqa: BLE001 — see docstring
                    self.faults.inc("daemon_errors")
                    break

    # -- receiver ----------------------------------------------------------

    def _send_ack(self, token: str, queue: str, seq: int) -> None:
        try:
            self.inner.publish(_ack_queue(token),
                               _env_frame(_ENV_ACK, seq, queue.encode(),
                                          b""))
        except (QueueClosed, ConnectionError, OSError):
            # a lost ack only costs a redelivery + dedup hit — but it
            # must be VISIBLE: a spike here is how an operator tells a
            # dying ack path from ordinary wire loss
            self.faults.inc("ack_send_failures")

    def _pop_ready(self, queue: str) -> bytes | None:
        """Next in-order held frame for ``queue``, if any."""
        with self._lock:
            for (q, token), held in self._held.items():
                if q != queue or not held:
                    continue
                exp = self._expected.get((q, token), 0)
                if exp in held:
                    payload = held.pop(exp)
                    self._expected[(q, token)] = exp + 1
                    if held:
                        self._gap_since[(q, token)] = time.monotonic()
                    else:
                        del self._held[(q, token)]
                        self._gap_since.pop((q, token), None)
                    return payload
        return None

    def _skip_dead_gaps(self, queue: str) -> None:
        """A gap older than gap_timeout_s means the sender gave up (or
        died): jump past it rather than stalling the queue forever."""
        now = time.monotonic()
        with self._lock:
            for (q, token), since in list(self._gap_since.items()):
                if q != queue or now - since < self._gap_timeout_s:
                    continue
                held = self._held.get((q, token))
                if not held:
                    self._gap_since.pop((q, token), None)
                    continue
                exp = self._expected.get((q, token), 0)
                nxt = min(held)
                self.faults.inc("lost", nxt - exp)
                self._expected[(q, token)] = nxt
                self._gap_since[(q, token)] = now

    def get(self, queue: str, timeout: float | None = None) -> bytes | None:
        if not self._match(queue):
            # pass-through queues keep the inner transport's REAL
            # blocking wait (condition variable / socket) — slicing
            # them into 0.1 s polls would reintroduce the reference's
            # sleep-polling on every idle control-plane wait
            return self.inner.get(queue, timeout)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            ready = self._pop_ready(queue)
            if ready is not None:
                return ready
            remain = (None if deadline is None
                      else deadline - time.monotonic())
            if remain is not None and remain <= 0:
                return None
            slice_t = 0.1 if remain is None else min(remain, 0.1)
            raw = self.inner.get(queue, slice_t)
            if raw is None:
                self._skip_dead_gaps(queue)
                continue
            parsed = _env_parse(raw)
            if parsed is None:
                if self._match(queue):
                    # every sender on a reliable queue envelopes its
                    # frames, so an unparseable one here is corruption
                    # that ate the envelope magic — drop it (no ack:
                    # the sender's redelivery repairs it), don't hand
                    # garbage (or a mis-ordered raw frame) to the app
                    self.faults.inc("corrupt_rejected")
                    continue
                return raw            # unwrapped control queue
            if parsed == "corrupt":
                self.faults.inc("corrupt_rejected")
                continue              # no ack -> sender redelivers
            kind, token, seq, payload, t_send = parsed
            if kind != _ENV_DATA:
                continue              # stray ack on a data queue
            if t_send is not None:
                # observed per ARRIVAL (dups included): this times the
                # channel, not the dedup policy above it
                self._hists.observe("transport_rtt",
                                    max(0.0, time.time() - t_send))
            self._send_ack(token, queue, seq)
            key = (queue, token)
            with self._lock:
                exp = self._expected.get(key, 0)
                if seq < exp or seq in self._held.get(key, {}):
                    self.faults.inc("dedup_hits")
                    continue
                if seq == exp:
                    self._expected[key] = exp + 1
                    if self._held.get(key):
                        self._gap_since[key] = time.monotonic()
                    else:
                        self._gap_since.pop(key, None)
                    return payload
                # future frame: hold for resequencing until the gap fills
                self._held.setdefault(key, {})[seq] = payload
                self._gap_since.setdefault(key, time.monotonic())
                self.faults.inc("resequenced")

    # -- plumbing ----------------------------------------------------------

    def purge(self, queues: Iterable[str] | None = None) -> None:
        self.inner.purge(queues)
        with self._lock:
            if queues is None:
                self._unacked.clear()
                self._held.clear()
                self._expected.clear()
                self._gap_since.clear()
            else:
                qs = set(queues)
                for d in (self._held, self._expected, self._gap_since):
                    for key in [k for k in d if k[0] in qs]:
                        del d[key]
                for key in [k for k in self._unacked if k[0] in qs]:
                    del self._unacked[key]

    def total_bytes_out(self) -> int:
        return self.inner.total_bytes_out()

    def bytes_out_snapshot(self) -> dict:
        return self.inner.bytes_out_snapshot()

    def stop(self, close_inner: bool = True) -> None:
        """Shut the daemon down; ``close_inner=False`` detaches from a
        SHARED underlying bus without closing it (crash simulation in
        tests: the 'process' dies, the network does not)."""
        self._closed.set()
        self._daemon.join(timeout=5.0)
        try:
            # our ack queue dies with our token: leave no orphaned
            # entries (and any unconsumed acks) in the broker store
            self.inner.purge([_ack_queue(self.token)])
        except (QueueClosed, ConnectionError, OSError):
            pass
        if close_inner:
            self.inner.close()
            if self._own_side:
                self._side.close()

    def close(self) -> None:
        self.stop(close_inner=True)


# --------------------------------------------------------------------------
# async data plane: background sender + receive prefetch
# --------------------------------------------------------------------------

class AsyncTransport(Transport):
    """Outermost wrapper that takes serialization and socket I/O off the
    training thread.

    * **Sender**: ``publish`` enqueues into a bounded FIFO drained by
      one background thread, so the hot loop never blocks on the wire.
      The payload may be a *callable* returning bytes (or a list of
      frame parts): the thunk — typically "fetch the device array to
      host + TENSOR-encode it" — then runs on the sender thread,
      overlapping microbatch k's device→host transfer and encode with
      the training thread's microbatch k+1 compute.  One thread drains
      the queue, so per-queue publish order (which the EpochEnd fence
      protocol and the reliable layer's seq numbers depend on) is
      exactly the enqueue order.
    * **Prefetch**: queues matching ``prefetch`` get a lazy background
      prefetcher that pulls up to ``prefetch_depth`` frames ahead of
      the consumer, so the next gradient/activation is already on-host
      when the hot loop asks.  The depth is deliberately small: shared
      per-cluster queues load-balance across same-stage clients, and a
      deep prefetch would steal peers' work.

    A sender-thread failure (``ChaosCrash``, a dead bus) is re-raised
    on the training thread's next ``publish``/``get`` — the participant
    dies where its process would have.  ``slice_gets`` bounds how long
    a pass-through blocking ``get`` may hold a lock-serialized inner
    transport (TcpTransport's single socket), so the sender thread can
    interleave its publishes.
    """

    deferred = True   # publish() accepts thunks / frame-part lists

    def __init__(self, inner: Transport, send_depth: int = 8,
                 prefetch: Iterable[str] = ("intermediate_queue*",
                                            "gradient_queue*"),
                 prefetch_depth: int = 2, recv_factory=None,
                 slice_gets: bool = False, wire=None, faults=None,
                 hists=None, tracer=None):
        super().__init__()
        self.inner = inner
        self._send_depth = max(1, send_depth)
        self._prefetch_patterns = tuple(prefetch)
        self._prefetch_depth = max(1, prefetch_depth)
        self._recv_factory = recv_factory
        self._slice_gets = slice_gets
        if wire is None:
            # fresh per instance, NOT the process-wide default: in-proc
            # cells build one AsyncTransport per participant, and a
            # shared registry would attribute every client's bytes to
            # each wire_client metrics record
            from split_learning_tpu.runtime.trace import WireCounters
            wire = WireCounters()
        self.wire = wire
        if hists is None:
            # per-participant, same reasoning as the wire counters
            from split_learning_tpu.runtime.trace import HistogramSet
            hists = HistogramSet()
        self.hists = hists
        # the participant's tracer rides the outermost transport layer
        # so ProtocolClient/ProtocolServer (which receive a pre-built
        # stack) find the one make_runtime_transport configured
        self.tracer = tracer
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        self._lock = make_lock("async")
        self._cv = make_condition("async", self._lock)
        self._sendq: collections.deque = collections.deque()
        self._inflight = 0      # popped by the sender, not yet published
        self._error: BaseException | None = None
        self._closed = threading.Event()
        self._prefetchers: dict[str, _Prefetcher] = {}
        self._sender = threading.Thread(target=self._send_loop,
                                        daemon=True, name="async-sender")
        self._sender.start()

    # -- sender ------------------------------------------------------------

    def _check_error(self):
        err = self._error
        if err is not None:
            raise err

    def publish(self, queue: str, payload) -> None:
        with self._cv:
            self._check_error()
            self._cv.wait_for(lambda: len(self._sendq) < self._send_depth
                              or self._error or self._closed.is_set())
            self._check_error()
            if self._closed.is_set():
                raise QueueClosed(queue)
            self._sendq.append((queue, payload))
            self.wire.note_send_depth(len(self._sendq))
            self._cv.notify_all()

    def _send_loop(self) -> None:
        while True:
            with self._cv:
                self._cv.wait_for(
                    lambda: self._sendq or self._closed.is_set())
                if not self._sendq:
                    return   # closed and drained
                queue, payload = self._sendq.popleft()
                self._inflight += 1   # flush must see this frame too
                self._cv.notify_all()
            try:
                if callable(payload):
                    t0 = time.perf_counter()
                    payload = payload()
                    dt = time.perf_counter() - t0
                    self.wire.add_encode(dt)
                    self.hists.observe("encode", dt)
                parts = (payload if isinstance(payload, (list, tuple))
                         else (payload,))
                for part in parts:
                    self.inner.publish(queue, part)
                    self.wire.count_out(queue, len(part))
            except BaseException as e:  # noqa: BLE001 — surfaced to the
                # training thread; the sender stops like a dead process
                with self._cv:
                    self._error = e
                    self._inflight -= 1
                    self._cv.notify_all()
                self.faults.inc("async_send_errors")
                return
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    def flush(self, timeout: float | None = 30.0) -> bool:
        """Block until every enqueued frame reached the inner transport
        (or the sender died).  False on timeout.  Covers the frame the
        sender has popped but not yet published — returning while the
        last UPDATE/STOP is mid-write would let the process exit (or
        the broker be torn down) under it."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cv:
            def drained():
                return ((not self._sendq and not self._inflight)
                        or self._error is not None)
            remain = (None if deadline is None
                      else max(0.0, deadline - time.monotonic()))
            self._cv.wait_for(drained, remain)
            return not self._sendq and not self._inflight

    # -- receive -----------------------------------------------------------

    def _match(self, queue: str) -> bool:
        return any(fnmatch.fnmatchcase(queue, p)
                   for p in self._prefetch_patterns)

    def _prefetcher(self, queue: str) -> "_Prefetcher":
        with self._lock:
            pf = self._prefetchers.get(queue)
            if pf is None:
                src = (self._recv_factory()
                       if self._recv_factory is not None else self.inner)
                pf = _Prefetcher(queue, src,
                                 own_src=self._recv_factory is not None,
                                 depth=self._prefetch_depth,
                                 wire=self.wire, faults=self.faults)
                self._prefetchers[queue] = pf
            return pf

    def get(self, queue: str, timeout: float | None = None) -> bytes | None:
        self._check_error()
        if self._match(queue):
            return self._prefetcher(queue).pop(timeout)
        if not self._slice_gets or (timeout is not None and timeout <= 0.1):
            raw = self.inner.get(queue, timeout)
        else:
            # lock-serialized inner (one TCP socket): bounded slices let
            # the sender thread's publishes interleave with this wait
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            raw = None
            while raw is None:
                self._check_error()
                remain = (None if deadline is None
                          else deadline - time.monotonic())
                if remain is not None and remain <= 0:
                    break
                raw = self.inner.get(
                    queue, 0.1 if remain is None else min(remain, 0.1))
        if raw is not None:
            self.wire.count_in(queue, len(raw))
        return raw

    # -- plumbing ----------------------------------------------------------

    def purge(self, queues: Iterable[str] | None = None) -> None:
        self.inner.purge(queues)
        with self._lock:
            pfs = list(self._prefetchers.items())
        for q, pf in pfs:
            if queues is None or q in set(queues):
                pf.clear()
        with self._cv:
            if queues is None:
                self._sendq.clear()
            else:
                qs = set(queues)
                self._sendq = collections.deque(
                    e for e in self._sendq if e[0] not in qs)
            self._cv.notify_all()

    def total_bytes_out(self) -> int:
        return self.inner.total_bytes_out()

    def bytes_out_snapshot(self) -> dict:
        return self.inner.bytes_out_snapshot()

    def stop(self, close_inner: bool = True) -> None:
        self.flush(timeout=10.0)
        self._closed.set()
        with self._cv:
            self._cv.notify_all()
        self._sender.join(timeout=5.0)
        with self._lock:
            pfs = list(self._prefetchers.values())
            self._prefetchers.clear()
        for pf in pfs:
            pf.stop()
        if close_inner:
            self.inner.close()

    def close(self) -> None:
        self.stop(close_inner=True)


class _Prefetcher:
    """One queue's bounded look-ahead buffer + puller thread."""

    def __init__(self, queue: str, src: Transport, own_src: bool,
                 depth: int, wire, faults):
        self.queue = queue
        self.src = src
        self._own_src = own_src
        self._depth = depth
        self._wire = wire
        self._faults = faults
        self._buf: collections.deque = collections.deque()
        self._cond = make_condition("prefetch")
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"prefetch-{queue}")
        self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cond:
                self._cond.wait_for(lambda: len(self._buf) < self._depth
                                    or self._closed)
                if self._closed:
                    return
            try:
                raw = self.src.get(self.queue, timeout=0.05)
            except QueueClosed:
                with self._cond:
                    self._closed = True
                    self._cond.notify_all()
                return
            except Exception:  # noqa: BLE001 — a transient transport
                # error must not kill the only thread filling the buffer
                if self._closed:
                    return
                self._faults.inc("prefetch_errors")
                time.sleep(0.1)
                continue
            if raw is not None:
                with self._cond:
                    self._buf.append(raw)
                    self._wire.count_in(self.queue, len(raw))
                    self._cond.notify_all()

    def pop(self, timeout: float | None) -> bytes | None:
        with self._cond:
            self._cond.wait_for(lambda: self._buf or self._closed,
                                timeout)
            if self._buf:
                raw = self._buf.popleft()
                self._cond.notify_all()
                return raw
            if self._closed:
                raise QueueClosed(self.queue)
            return None

    def clear(self) -> None:
        with self._cond:
            self._buf.clear()
            self._cond.notify_all()

    def stop(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        if self._own_src:
            try:
                self.src.close()
            except (QueueClosed, ConnectionError, OSError):
                pass


def make_transport(kind: str, host: str = "127.0.0.1",
                   port: int = 5672, shards: int = 1,
                   faults=None) -> Transport:
    if kind == "inproc":
        return InProcTransport()
    if kind == "tcp":
        if shards > 1:
            # broker.shards: every queue is owned by exactly one of
            # the shard endpoints at ports port..port+shards-1
            return ShardedTcpTransport(host, port, shards,
                                       faults=faults)
        return TcpTransport(host, port, faults=faults)
    raise ValueError(f"unknown transport kind {kind!r}")
