"""Message transports for the control/data plane.

The reference's transport is a RabbitMQ broker spoken via pika
(``/root/reference/src/Server.py:57-61``); clients *poll* with
``basic_get`` + 0.5 s sleeps (``src/RpcClient.py:37-41``).  Here the same
named-queue semantics live behind one small interface with two backends:

* :class:`InProcTransport` — thread-safe in-process queues.  The whole
  training cell (server + N clients) runs in one process; this is the
  TPU-native default (the data plane then usually bypasses the bus
  entirely via the compiled mesh pipeline).
* :class:`TcpTransport` + :class:`Broker` — a ~150-line length-prefixed
  TCP broker giving true multi-process / multi-host parity with the
  reference's deployment shape, without an external Erlang dependency.

Blocking ``get`` uses real waits (condition variables / socket blocking),
not the reference's sleep-polling.
"""

from __future__ import annotations

import collections
import socket
import struct
import threading
import time
from typing import Iterable


class QueueClosed(Exception):
    pass


class Transport:
    """Named-queue message transport (byte payloads).

    Concrete transports call :meth:`_count` from ``publish`` so tests
    and metrics can audit wire traffic (e.g. FLEX's no-upload rounds
    must move no weight bytes) via :attr:`bytes_out`.
    """

    def __init__(self):
        # own lock: one transport is shared by server + client threads
        self._count_lock = threading.Lock()
        self.bytes_out: dict = {}

    def publish(self, queue: str, payload: bytes) -> None:
        raise NotImplementedError

    def _count(self, queue: str, payload: bytes) -> None:
        with self._count_lock:
            self.bytes_out[queue] = (self.bytes_out.get(queue, 0)
                                     + len(payload))

    def total_bytes_out(self) -> int:
        with self._count_lock:
            return sum(self.bytes_out.values())

    def bytes_out_snapshot(self) -> dict:
        """Consistent copy of the per-queue publish-byte counters."""
        with self._count_lock:
            return dict(self.bytes_out)

    def get(self, queue: str, timeout: float | None = None) -> bytes | None:
        """Pop one message; block up to ``timeout`` (None = forever).
        Returns None on timeout."""
        raise NotImplementedError

    def purge(self, queues: Iterable[str] | None = None) -> None:
        """Drop pending messages (all queues if None) — the reference's
        ``delete_old_queues`` hygiene (``src/Utils.py:8-32``)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcTransport(Transport):
    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: dict[str, collections.deque] = \
            collections.defaultdict(collections.deque)
        self._closed = False

    def publish(self, queue: str, payload: bytes) -> None:
        self._count(queue, payload)
        with self._cond:
            if self._closed:
                raise QueueClosed(queue)
            self._queues[queue].append(payload)
            self._cond.notify_all()

    def get(self, queue: str, timeout: float | None = None) -> bytes | None:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._closed or self._queues[queue], timeout)
            if self._closed:
                raise QueueClosed(queue)
            if not ok:
                return None
            return self._queues[queue].popleft()

    def qsize(self, queue: str) -> int:
        with self._lock:
            return len(self._queues[queue])

    def purge(self, queues: Iterable[str] | None = None) -> None:
        with self._cond:
            if queues is None:
                self._queues.clear()
            else:
                for q in queues:
                    self._queues.pop(q, None)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# --------------------------------------------------------------------------
# TCP broker
# --------------------------------------------------------------------------
# Frame: 1-byte op | 4-byte BE queue-name len | name | 8-byte BE payload len
# | payload.  Ops: P=publish, G=get(blocking; payload = 8-byte BE timeout in
# ms, 0 = forever), X=purge, R=reply (broker->client; zero payload len and
# flag 0xFF means timeout).

_OP_PUB, _OP_GET, _OP_PURGE, _OP_REPLY = b"P", b"G", b"X", b"R"
_TIMEOUT_SENTINEL = 0xFFFFFFFFFFFFFFFF


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, op: bytes, name: bytes,
                payload: bytes) -> None:
    sock.sendall(op + struct.pack(">I", len(name)) + name
                 + struct.pack(">Q", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> tuple[bytes, bytes, bytes]:
    op = _recv_exact(sock, 1)
    (nlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    name = _recv_exact(sock, nlen)
    (plen,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if plen == _TIMEOUT_SENTINEL:
        return op, name, None  # type: ignore[return-value]
    return op, name, _recv_exact(sock, plen)


class Broker:
    """Threaded TCP message broker (one thread per connection)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._store = InProcTransport()
        self._sock = socket.create_server((host, port))
        self.host, self.port = self._sock.getsockname()[:2]
        self._threads: list[threading.Thread] = []
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket):
        try:
            while True:
                op, name, payload = _recv_frame(conn)
                queue = name.decode()
                if op == _OP_PUB:
                    self._store.publish(queue, payload)
                elif op == _OP_GET:
                    (ms,) = struct.unpack(">Q", payload)
                    timeout = None if ms == 0 else ms / 1000.0
                    try:
                        msg = self._store.get(queue, timeout)
                    except QueueClosed:
                        return
                    if msg is None:
                        conn.sendall(_OP_REPLY + struct.pack(">I", 0)
                                     + struct.pack(">Q", _TIMEOUT_SENTINEL))
                    else:
                        _send_frame(conn, _OP_REPLY, b"", msg)
                elif op == _OP_PURGE:
                    self._store.purge(None if not payload
                                      else payload.decode().split(","))
        except (ConnectionError, OSError):
            return

    def close(self):
        self._running = False
        self._store.close()
        try:
            self._sock.close()
        except OSError:
            pass


class TcpTransport(Transport):
    """Client of a :class:`Broker`. One socket per transport instance;
    safe for one thread (create one per worker thread)."""

    def __init__(self, host: str, port: int, connect_timeout: float = 30.0):
        super().__init__()
        # the broker may still be coming up (simultaneous launch): retry
        # with backoff instead of failing the whole client process
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=10.0)
                self._sock.settimeout(None)
                break
            except (ConnectionRefusedError, ConnectionResetError,
                    TimeoutError):
                # only not-up-yet errors; bad hostnames etc. fail fast
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
        self._lock = threading.Lock()

    def publish(self, queue: str, payload: bytes) -> None:
        self._count(queue, payload)
        with self._lock:
            _send_frame(self._sock, _OP_PUB, queue.encode(), payload)

    def get(self, queue: str, timeout: float | None = None) -> bytes | None:
        ms = 0 if timeout is None else max(1, int(timeout * 1000))
        with self._lock:
            _send_frame(self._sock, _OP_GET, queue.encode(),
                        struct.pack(">Q", ms))
            op, _, payload = _recv_frame(self._sock)
            if op != _OP_REPLY:
                raise ConnectionError(f"unexpected broker reply op {op!r}")
            return payload  # None on timeout

    def purge(self, queues: Iterable[str] | None = None) -> None:
        payload = b"" if queues is None else ",".join(queues).encode()
        with self._lock:
            _send_frame(self._sock, _OP_PURGE, b"", payload)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def make_transport(kind: str, host: str = "127.0.0.1",
                   port: int = 5672) -> Transport:
    if kind == "inproc":
        return InProcTransport()
    if kind == "tcp":
        return TcpTransport(host, port)
    raise ValueError(f"unknown transport kind {kind!r}")
