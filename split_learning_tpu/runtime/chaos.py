"""Deterministic fault injection for the message transports.

The protocol stack is only as trustworthy as its worst recovery path,
and recovery paths are exactly the code normal runs never execute.
:class:`ChaosTransport` wraps any :class:`~split_learning_tpu.runtime
.bus.Transport` and injects the full failure vocabulary of a real
deployment — dropped, duplicated, reordered, delayed and bit-corrupted
messages, plus scripted process crashes — **reproducibly**: every
probabilistic decision is drawn from a per-queue RNG seeded by
``(chaos.seed, queue_name)``, so a failing run replays from one integer
regardless of thread scheduling (each fault roll consumes a fixed number
of draws whether or not it fires, keeping the per-queue stream aligned).

Faults are injected on the *publish* side, which models every channel
failure the receiver can observe; the layers that must survive them are

* ``runtime/protocol.py`` — checksummed frames reject corruption before
  unpickling;
* ``runtime/bus.py ReliableTransport`` — seq/ack/redeliver + dedup +
  resequencing turns drops/dups/reordering back into an exact in-order
  stream;
* the protocol server/client — barrier deadlines, elastic drop and
  crash-atomic checkpoints absorb scripted crashes.

Stack order: ``ReliableTransport(ChaosTransport(bus))`` — chaos sits
*below* reliability, exactly where the physical network does, so
redelivered frames roll fresh faults too.

Determinism caveat: the telemetry heartbeat emitter
(``runtime/telemetry.py``, on by default) publishes on ``rpc_queue``
from a timer thread, so that queue's per-message fault draws — and any
crash script counting ``rpc_queue`` publishes — interleave with
wall-clock timing rather than protocol position.  Fault *masking* is
timing-independent (that is what the reliable layer proves), but a
cell that needs the exact ``rpc_queue`` fault pattern to replay
frame-for-frame should set ``observability.heartbeat-interval: 0``;
the data-plane queues' streams are unaffected (the emitter never
touches them).

Scripted crash points model "client c2 dies right after sending its 2nd
stage-1 activation": when the owning participant's Nth publish to a
matching queue completes, :class:`ChaosCrash` is raised out of
``publish`` and the participant's process/thread unwinds.  The message
itself IS sent first (the failure mode that matters — a crash before
the send is indistinguishable from a drop).
"""

from __future__ import annotations

import fnmatch
import random
import threading
import zlib
from typing import Iterable

from split_learning_tpu.analysis.locks import make_lock
from split_learning_tpu.config import ChaosConfig, Config
from split_learning_tpu.runtime import blackbox
from split_learning_tpu.runtime.bus import (
    AsyncTransport, QueueClosed, ReliableTransport, Transport,
    make_transport,
)


class ChaosCrash(RuntimeError):
    """Scripted process death (chaos.crash) — raised out of publish()."""


class ChaosTransport(Transport):
    """Seeded fault-injecting wrapper over any transport.

    ``name`` identifies the owning participant for crash scripts.  All
    fault state (RNGs, reorder stash, crash counters) is per-instance:
    give every simulated process its own wrapper over the shared bus.
    """

    def __init__(self, inner: Transport, cfg: ChaosConfig, name: str = "",
                 faults=None, side: Transport | None = None):
        super().__init__()
        self.inner = inner
        # delayed frames publish from Timer threads; over TCP they must
        # not contend for the main socket's lock (a blocking get holds
        # it indefinitely) — give them their own connection via ``side``
        self._side = side if side is not None else inner
        self.cfg = cfg
        self.name = name
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        self._lock = make_lock("chaos")
        self._rngs: dict[str, random.Random] = {}
        self._stash: dict[str, bytes] = {}     # reorder slot per queue
        # scripted crash points owned by this participant (copies: the
        # publish counter lives in the spec under "_n")
        self._crash = [dict(s) for s in cfg.crash
                       if s.get("client") in ("*", name)]
        # sticky death: once a crash point fires, the participant IS
        # dead — every later publish/get on this wrapper re-raises.
        # Matters because the first ChaosCrash can surface on a
        # background thread (the telemetry heartbeat emitter) whose
        # error handling must not resurrect the "process"; the
        # training thread then dies at its next transport op, exactly
        # like AsyncTransport's deferred-error re-raise.
        self._crashed = False
        self._timers: list[threading.Timer] = []

    def _check_crashed(self) -> None:
        if self._crashed:
            raise ChaosCrash(
                f"scripted crash: {self.name or '?'} is dead")

    def _record_crash(self, queue: str) -> None:
        """Sticky ChaosCrash = this participant's process death: the
        flight recorder dumps NOW, exactly like a signal handler would
        — the unwinding 'process' gets no later chance."""
        blackbox.record("chaos_crash", queue=queue,
                        name=self.name or None)
        blackbox.dump(f"chaos_crash:{self.name or '?'}")

    def _rng(self, queue: str) -> random.Random:
        r = self._rngs.get(queue)
        if r is None:
            r = random.Random(zlib.crc32(
                f"{self.cfg.seed}:{queue}".encode()))
            self._rngs[queue] = r
        return r

    def _match(self, queue: str) -> bool:
        return any(fnmatch.fnmatchcase(queue, p)
                   for p in self.cfg.queues)

    def _crash_due(self, queue: str) -> bool:
        due = False
        for spec in self._crash:
            if fnmatch.fnmatchcase(queue, spec.get("queue", "*")):
                spec["_n"] = spec.get("_n", 0) + 1
                if spec["_n"] == int(spec.get("after", 1)):
                    due = True
        return due

    def _late_publish(self, queue: str, payload: bytes) -> None:
        try:
            self._side.publish(queue, payload)
        except (QueueClosed, ConnectionError, OSError):
            # the run ended before the delayed frame landed; counted so
            # a sweep can tell "delayed into teardown" from a real drop
            self.faults.inc("late_drops")

    def publish(self, queue: str, payload: bytes) -> None:
        self._check_crashed()
        with self._lock:
            # crash scripts fire on ANY queue (a process dies wherever
            # the script says); probabilistic faults only on cfg.queues
            crash = self._crash_due(queue)
        if not self._match(queue):
            self.inner.publish(queue, payload)
            if crash:
                self.faults.inc("crashes")
                self._crashed = True
                self._record_crash(queue)
                raise ChaosCrash(
                    f"scripted crash: {self.name or '?'} dies at "
                    f"publish to {queue}")
            return
        cfg = self.cfg
        with self._lock:
            r = self._rng(queue)
            # fixed draw count per publish keeps the per-queue fault
            # stream aligned whatever fires
            drop = r.random() < cfg.drop
            dup = r.random() < cfg.duplicate
            reorder = r.random() < cfg.reorder
            corrupt = r.random() < cfg.corrupt
            delay = r.random() < cfg.delay
            pos_f = r.random()

            out = payload
            if corrupt and payload:
                i = int(pos_f * len(payload)) % len(payload)
                out = payload[:i] + bytes([payload[i] ^ 0xFF]) \
                    + payload[i + 1:]
                self.faults.inc("corruptions")
            sends = []
            if drop:
                self.faults.inc("drops")
            else:
                sends.append(out)
                if dup:
                    sends.append(out)
                    self.faults.inc("duplicates")
            # reorder: stash one frame; it rides out AFTER the next
            # publish to the same queue (a classic 2-swap)
            prior = self._stash.pop(queue, None)
            emit = []
            for s in sends:
                if reorder and queue not in self._stash:
                    self._stash[queue] = s
                    self.faults.inc("reorders")
                else:
                    emit.append(s)
            if prior is not None:
                emit.append(prior)
            if delay and cfg.delay_s > 0 and emit:
                self.faults.inc("delays")
                self._timers = [t for t in self._timers if t.is_alive()]
                for s in emit:
                    t = threading.Timer(cfg.delay_s, self._late_publish,
                                        (queue, s))
                    t.daemon = True
                    self._timers.append(t)
                    t.start()
                emit = []
        # flight-recorder feed: the fired faults with their queue —
        # the per-name counter feed (FaultCounters.inc) has no queue
        # context, and the postmortem wants "what was injected WHERE"
        if blackbox.enabled():
            fired = [n for n, f in (("drop", drop), ("dup", dup),
                                    ("reorder", reorder),
                                    ("corrupt", corrupt),
                                    ("delay", delay)) if f]
            if fired:
                blackbox.record("chaos", queue=queue, faults=fired,
                                name=self.name or None)
        for s in emit:
            self.inner.publish(queue, s)
        if crash:
            self.faults.inc("crashes")
            self._crashed = True
            self._record_crash(queue)
            raise ChaosCrash(
                f"scripted crash: {self.name or '?'} dies at publish "
                f"to {queue}")

    def get(self, queue: str, timeout: float | None = None):
        self._check_crashed()
        return self.inner.get(queue, timeout)

    def purge(self, queues: Iterable[str] | None = None) -> None:
        self.inner.purge(queues)
        with self._lock:
            if queues is None:
                self._stash.clear()
            else:
                for q in queues:
                    self._stash.pop(q, None)

    def total_bytes_out(self) -> int:
        return self.inner.total_bytes_out()

    def bytes_out_snapshot(self) -> dict:
        return self.inner.bytes_out_snapshot()

    def stop(self, close_inner: bool = True) -> None:
        with self._lock:
            for t in self._timers:
                t.cancel()
            self._timers.clear()
        if close_inner:
            self.inner.close()
            if self._side is not self.inner:
                self._side.close()

    def close(self) -> None:
        self.stop(close_inner=True)


def make_runtime_transport(cfg: Config, name: str,
                           faults=None) -> Transport:
    """Build one participant's full transport stack from config.

    Over TCP the chaos delay timers and the reliable redelivery/ack
    daemon each get their own broker connection (a blocked ``get``
    serializes a TcpTransport's socket, so background publishers must
    not share the main one).  The daemon's connection is itself
    chaos-wrapped so redelivered frames roll fresh faults, keeping the
    chaos-below-reliability layering identical across backends.

    ``transport.async-send`` (default on) adds :class:`AsyncTransport`
    as the OUTERMOST layer: the training thread enqueues encode thunks
    and the background sender drives the reliable/chaos/bus stack, so
    redelivery envelopes and fault injection see exactly the same frame
    stream as the synchronous path.  Data-plane receive prefetch gets a
    dedicated broker connection when there is no reliable layer (the
    reliable receiver's dedup/resequence state must stay on ONE
    instance per queue, so with it the prefetcher shares the stack).

    The participant's :class:`~split_learning_tpu.runtime.spans.Tracer`
    rides the outermost layer (``bus.tracer``) so the protocol roles
    pick up the configured one; the chaos/reliable layers below are
    deliberately trace-transparent — they move payload bytes (and the
    trace context inside them) untouched, apart from the corruption
    chaos is paid to inject."""
    tcp = cfg.transport.kind == "tcp"
    shards = getattr(getattr(cfg, "broker", None), "shards", 1)

    def mk() -> Transport:
        return make_transport(cfg.transport.kind, cfg.transport.host,
                              cfg.transport.port, shards=shards,
                              faults=faults)

    bus = mk()
    if cfg.chaos.enabled:
        bus = ChaosTransport(bus, cfg.chaos, name=name, faults=faults,
                             side=mk() if tcp else None)
    if cfg.transport.reliable:
        side = None
        if tcp:
            side = mk()
            if cfg.chaos.enabled:
                # probabilistic faults only: a scripted crash models the
                # PROCESS dying, which the main-path wrapper already
                # does — the repair daemon must not crash twice
                import dataclasses
                side = ChaosTransport(
                    side, dataclasses.replace(cfg.chaos, crash=()),
                    name=f"{name}.redeliver", faults=faults)
        bus = ReliableTransport(
            bus, sender=name, patterns=cfg.transport.reliable_queues,
            side=side, redeliver_s=cfg.transport.redeliver_s,
            max_redeliver=cfg.transport.max_redeliver, faults=faults)
    if cfg.transport.async_send:
        from split_learning_tpu.runtime.spans import make_tracer
        recv_factory = (mk if tcp and not cfg.transport.reliable
                        else None)
        bus = AsyncTransport(
            bus, send_depth=cfg.transport.send_depth,
            prefetch_depth=cfg.transport.prefetch_depth,
            recv_factory=recv_factory, slice_gets=tcp, faults=faults,
            tracer=make_tracer(cfg, name))
    return bus
